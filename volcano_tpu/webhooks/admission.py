"""Admission chain: mutate then validate on object create.

Reference parity: pkg/webhooks (router/admission.go paths
/jobs/{validate,mutate}, /queues/..., /podgroups/..., /hypernodes/
validate).  Standalone equivalent: the cluster applies this chain on
create — a rejection raises AdmissionError before anything persists.
"""

from __future__ import annotations

import re
from typing import Optional

from volcano_tpu.api.types import DEFAULT_QUEUE, JobEvent
from volcano_tpu.api.vcjob import VCJob

DNS1123 = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
MAX_NAME_LEN = 63


class AdmissionError(ValueError):
    """Raised when a webhook rejects an object."""


# -- jobs -------------------------------------------------------------

def mutate_job(job: VCJob) -> VCJob:
    """Defaulting (reference admission/jobs/mutate): queue, task names,
    minAvailable, task minAvailable, scheduler name."""
    if not job.queue:
        job.queue = DEFAULT_QUEUE
    if not job.scheduler_name:
        job.scheduler_name = "volcano-tpu"
    for i, task in enumerate(job.tasks):
        if not task.name:
            task.name = f"task-{i}"
        if task.min_available is None:
            task.min_available = task.replicas
    if job.min_available <= 0:
        job.min_available = job.total_replicas()
    return job


def validate_job(job: VCJob, cluster=None) -> None:
    """Spec sanity (reference admission/jobs/validate)."""
    from volcano_tpu.controllers.job.plugins import job_plugin_exists

    if not DNS1123.match(job.name) or len(job.name) > MAX_NAME_LEN:
        raise AdmissionError(
            f"job name {job.name!r} must be a DNS-1123 label "
            f"(<= {MAX_NAME_LEN} chars)")
    if not job.tasks:
        raise AdmissionError("job must declare at least one task")
    names = [t.name for t in job.tasks]
    if len(set(names)) != len(names):
        raise AdmissionError(f"duplicate task names: {names}")
    total = 0
    for task in job.tasks:
        if not DNS1123.match(task.name):
            raise AdmissionError(f"task name {task.name!r} invalid")
        if task.replicas < 0:
            raise AdmissionError(f"task {task.name}: replicas < 0")
        if task.min_available is not None and \
                task.min_available > task.replicas:
            raise AdmissionError(
                f"task {task.name}: minAvailable {task.min_available} > "
                f"replicas {task.replicas}")
        total += task.replicas
        if task.depends_on:
            for dep in task.depends_on.name:
                if dep not in names:
                    raise AdmissionError(
                        f"task {task.name} dependsOn unknown task {dep}")
    if job.min_available < 0:
        raise AdmissionError("minAvailable must be >= 0")
    if job.min_available > total:
        raise AdmissionError(
            f"minAvailable {job.min_available} > total replicas {total}")
    if job.min_success is not None and job.min_success > total:
        raise AdmissionError(
            f"minSuccess {job.min_success} > total replicas {total}")
    if job.max_retry < 0:
        raise AdmissionError("maxRetry must be >= 0")
    for plugin_name in job.plugins:
        if not job_plugin_exists(plugin_name):
            raise AdmissionError(f"unknown job plugin {plugin_name!r}")
    for policy in job.policies:
        if policy.event is None and not policy.events and \
                policy.exit_code is None:
            raise AdmissionError("policy must set event(s) or exitCode")
        if policy.exit_code == 0:
            raise AdmissionError("policy exitCode 0 is not allowed")
    if job.network_topology is not None and \
            job.network_topology.highest_tier_allowed < 1:
        raise AdmissionError("networkTopology.highestTierAllowed must be >= 1")
    if cluster is not None and job.queue:
        if job.queue not in cluster.queues:
            raise AdmissionError(f"queue {job.queue!r} does not exist")
        if not cluster.queues[job.queue].is_open():
            raise AdmissionError(f"queue {job.queue!r} is not open")


# -- queues -----------------------------------------------------------

def validate_queue(queue, cluster=None) -> None:
    if not DNS1123.match(queue.name):
        raise AdmissionError(f"queue name {queue.name!r} invalid")
    if queue.weight <= 0:
        raise AdmissionError("queue weight must be > 0")
    if cluster is not None and queue.parent:
        if queue.parent not in cluster.queues:
            raise AdmissionError(
                f"parent queue {queue.parent!r} does not exist")
        # reject hierarchy cycles
        seen = {queue.name}
        cur = queue.parent
        while cur:
            if cur in seen:
                raise AdmissionError(
                    f"queue hierarchy cycle through {cur!r}")
            seen.add(cur)
            parent = cluster.queues.get(cur)
            cur = parent.parent if parent else ""


# -- podgroups / hypernodes -------------------------------------------

def validate_podgroup(pg) -> None:
    if pg.min_member < 0:
        raise AdmissionError("minMember must be >= 0")
    if pg.min_task_member:
        for name, n in pg.min_task_member.items():
            if n < 0:
                raise AdmissionError(f"minTaskMember[{name}] must be >= 0")


def validate_hypernode(hn) -> None:
    if hn.tier < 1:
        raise AdmissionError("hypernode tier must be >= 1")
    if not hn.members:
        raise AdmissionError("hypernode must have members")
    for m in hn.members:
        if m.kind not in ("Node", "HyperNode"):
            raise AdmissionError(f"invalid member kind {m.kind!r}")
        if not (m.exact or m.regex or m.labels):
            raise AdmissionError("member selector must be set")


class AdmissionChain:
    """The webhook pipeline a Cluster applies on create."""

    def admit_job(self, job: VCJob, cluster=None) -> VCJob:
        job = mutate_job(job)
        validate_job(job, cluster)
        return job

    def admit_queue(self, queue, cluster=None):
        validate_queue(queue, cluster)
        return queue

    def admit_podgroup(self, pg, cluster=None):
        validate_podgroup(pg)
        return pg

    def admit_hypernode(self, hn, cluster=None):
        validate_hypernode(hn)
        return hn


def default_admission() -> AdmissionChain:
    return AdmissionChain()
