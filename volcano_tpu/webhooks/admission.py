"""Admission chain: mutate then validate on object create.

Reference parity: pkg/webhooks (router/admission.go paths
/jobs/{validate,mutate}, /queues/..., /podgroups/..., /hypernodes/
validate).  Standalone equivalent: the cluster applies this chain on
create — a rejection raises AdmissionError before anything persists.
"""

from __future__ import annotations

import re

from volcano_tpu.api.types import DEFAULT_QUEUE
from volcano_tpu.api.vcjob import VCJob

DNS1123 = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
MAX_NAME_LEN = 63


class AdmissionError(ValueError):
    """Raised when a webhook rejects an object."""


# -- jobs -------------------------------------------------------------

def mutate_job(job: VCJob) -> VCJob:
    """Defaulting (reference admission/jobs/mutate): queue, task names,
    minAvailable, task minAvailable, scheduler name."""
    if not job.queue:
        job.queue = DEFAULT_QUEUE
    if not job.scheduler_name:
        job.scheduler_name = "volcano-tpu"
    for i, task in enumerate(job.tasks):
        if not task.name:
            task.name = f"task-{i}"
        if task.min_available is None:
            task.min_available = task.replicas
    if job.min_available <= 0:
        job.min_available = job.total_replicas()
    _mutate_mpi(job)
    _mutate_elastic(job)
    return job


def _mutate_elastic(job: VCJob) -> None:
    """Elastic defaulting: a job declaring min/max-slices starts at
    its floor unless it names a size — submit small, let the
    scheduler grow it into idle capacity (actions/elastic.py)."""
    from volcano_tpu.api import elastic as eapi
    if not eapi.is_elastic(job):
        return
    ann = job.annotations
    if eapi.ELASTIC_SLICES_ANNOTATION not in ann:
        ann[eapi.ELASTIC_SLICES_ANNOTATION] = \
            ann[eapi.ELASTIC_MIN_SLICES_ANNOTATION]


def _mutate_mpi(job: VCJob) -> None:
    """MPI mutating plugin (reference admission/jobs/plugins/mpi):
    the launcher must not start before the workers exist, so default
    the master task's dependsOn to the worker task."""
    if "mpi" not in job.plugins:
        return
    master, worker = "master", "worker"
    for arg in job.plugins.get("mpi") or []:
        if arg.startswith("--master="):
            master = arg.split("=", 1)[1]
        elif arg.startswith("--worker="):
            worker = arg.split("=", 1)[1]
    from volcano_tpu.api.vcjob import DependsOn
    if worker not in {t.name for t in job.tasks}:
        return   # never inject a dependency on a task that isn't there
    for task in job.tasks:
        if task.name == master and task.depends_on is None:
            task.depends_on = DependsOn(name=[worker])


def validate_job(job: VCJob, cluster=None) -> None:
    """Spec sanity (reference admission/jobs/validate)."""
    from volcano_tpu.controllers.job.plugins import job_plugin_exists

    if not DNS1123.match(job.name) or len(job.name) > MAX_NAME_LEN:
        raise AdmissionError(
            f"job name {job.name!r} must be a DNS-1123 label "
            f"(<= {MAX_NAME_LEN} chars)")
    if not job.tasks:
        raise AdmissionError("job must declare at least one task")
    names = [t.name for t in job.tasks]
    if len(set(names)) != len(names):
        raise AdmissionError(f"duplicate task names: {names}")
    total = 0
    for task in job.tasks:
        if not DNS1123.match(task.name):
            raise AdmissionError(f"task name {task.name!r} invalid")
        if task.replicas < 0:
            raise AdmissionError(f"task {task.name}: replicas < 0")
        if task.min_available is not None and \
                task.min_available > task.replicas:
            raise AdmissionError(
                f"task {task.name}: minAvailable {task.min_available} > "
                f"replicas {task.replicas}")
        total += task.replicas
        if task.depends_on:
            for dep in task.depends_on.name:
                if dep not in names:
                    raise AdmissionError(
                        f"task {task.name} dependsOn unknown task {dep}")
    if job.min_available < 0:
        raise AdmissionError("minAvailable must be >= 0")
    if job.min_available > total:
        raise AdmissionError(
            f"minAvailable {job.min_available} > total replicas {total}")
    if job.min_success is not None and job.min_success > total:
        raise AdmissionError(
            f"minSuccess {job.min_success} > total replicas {total}")
    if job.max_retry < 0:
        raise AdmissionError("maxRetry must be >= 0")
    for plugin_name in job.plugins:
        if not job_plugin_exists(plugin_name):
            raise AdmissionError(f"unknown job plugin {plugin_name!r}")
    for policy in job.policies:
        if policy.event is None and not policy.events and \
                policy.exit_code is None:
            raise AdmissionError("policy must set event(s) or exitCode")
        if policy.exit_code == 0:
            raise AdmissionError("policy exitCode 0 is not allowed")
    if job.network_topology is not None and \
            job.network_topology.highest_tier_allowed is not None and \
            job.network_topology.highest_tier_allowed < 1:
        raise AdmissionError("networkTopology.highestTierAllowed must be >= 1")
    subgroup_nts = {}
    for task in job.tasks:
        nt = getattr(task, "network_topology", None)
        if nt is None:
            continue
        if not task.subgroup:
            raise AdmissionError(
                f"task {task.name!r}: networkTopology requires subGroup "
                "(per-task topology binds a subgroup gang to a domain)")
        if nt.highest_tier_allowed is not None and \
                nt.highest_tier_allowed < 1:
            raise AdmissionError(
                f"task {task.name!r}: networkTopology.highestTierAllowed "
                "must be >= 1")
        prev = subgroup_nts.setdefault(task.subgroup, nt)
        if prev is not nt and (prev.mode is not nt.mode or
                               prev.highest_tier_allowed !=
                               nt.highest_tier_allowed):
            raise AdmissionError(
                f"task {task.name!r}: conflicting networkTopology for "
                f"subGroup {task.subgroup!r} (one constraint per "
                "subgroup gang)")
    _validate_elastic(job)
    if cluster is not None and job.queue:
        if job.queue not in cluster.queues:
            raise AdmissionError(f"queue {job.queue!r} does not exist")
        if not cluster.queues[job.queue].is_open():
            raise AdmissionError(f"queue {job.queue!r} is not open")


def _validate_elastic(job: VCJob) -> None:
    """Elastic-range sanity: integers with 1 <= min <= slices <= max,
    and the TPU worker replicas must divide evenly by the slice count
    (the quotient — pods-per-slice — is the invariant every resize
    preserves, so a fractional one can never be materialized)."""
    from volcano_tpu.api import elastic as eapi
    from volcano_tpu.api.resource import TPU
    ann = job.annotations
    declared = [k for k in (eapi.ELASTIC_MIN_SLICES_ANNOTATION,
                            eapi.ELASTIC_MAX_SLICES_ANNOTATION)
                if k in ann]
    if not declared:
        return
    if len(declared) == 1:
        raise AdmissionError(
            f"elastic jobs must declare BOTH min-slices and "
            f"max-slices (got only {declared[0]})")
    rng = eapi.elastic_range(job)
    if rng is None:
        raise AdmissionError(
            "elastic min/max-slices must be integers with "
            "1 <= min <= max")
    if any(t.subgroup for t in job.tasks):
        # the resize machinery scales ONE process grid (the jax
        # plugin's elastic env path keys slice ids on rank blocks);
        # subgrouped gangs pin slice ids to static subgroups, which a
        # resize cannot re-shape — reject instead of mis-meshing
        raise AdmissionError(
            "elastic ranges are not supported on subgrouped gangs "
            "(the subgroup count pins the slice topology)")
    slices = eapi.current_slices(job)
    if not rng[0] <= slices <= rng[1]:
        raise AdmissionError(
            f"elastic slices {slices} outside the declared range "
            f"[{rng[0]}, {rng[1]}]")
    scalable = [t for t in job.tasks
                if float((t.template_pod().resource_requests()
                          .get(TPU)) or 0) > 0] or job.tasks
    for task in scalable:
        if task.replicas % slices:
            raise AdmissionError(
                f"task {task.name!r}: {task.replicas} replicas do not "
                f"divide into {slices} slice(s) — elastic resize "
                f"needs an integral pods-per-slice")


# -- queues -----------------------------------------------------------

# reference-style hierarchy annotations (KubeHierarchyAnnotationKey):
# consumed by hdrf when annotation-driven hierarchy is used in place
# of the parent field
HIERARCHY_ANNOTATION = "volcano-tpu.io/hierarchy"
HIERARCHY_WEIGHTS_ANNOTATION = "volcano-tpu.io/hierarchy-weights"


def mutate_queue(queue):
    """Create-path defaulting (reference admission/queues/mutate/
    mutate_queue.go:40): weight 0 -> 1, and hierarchy annotations are
    rooted (`a/b` -> `root/a/b`, weights `2/1` -> `1/2/1`) so every
    hierarchy walk shares one root.  reclaimable/state defaulting is
    the dataclass's (a wire create without those fields lands on
    True/OPEN already)."""
    if queue.weight <= 0:
        queue.weight = 1
    h = queue.annotations.get(HIERARCHY_ANNOTATION, "")
    hw = queue.annotations.get(HIERARCHY_WEIGHTS_ANNOTATION, "")
    if h and hw and h.split("/", 1)[0] != "root":
        queue.annotations[HIERARCHY_ANNOTATION] = f"root/{h}"
        queue.annotations[HIERARCHY_WEIGHTS_ANNOTATION] = f"1/{hw}"
    return queue


def validate_queue(queue, cluster=None) -> None:
    if not DNS1123.match(queue.name):
        raise AdmissionError(f"queue name {queue.name!r} invalid")
    if queue.weight <= 0:
        raise AdmissionError("queue weight must be > 0")
    if cluster is not None and queue.parent:
        if queue.parent not in cluster.queues:
            raise AdmissionError(
                f"parent queue {queue.parent!r} does not exist")
        # reject hierarchy cycles
        seen = {queue.name}
        cur = queue.parent
        while cur:
            if cur in seen:
                raise AdmissionError(
                    f"queue hierarchy cycle through {cur!r}")
            seen.add(cur)
            parent = cluster.queues.get(cur)
            cur = parent.parent if parent else ""


# -- podgroups / hypernodes -------------------------------------------

# namespace annotation naming that namespace's default queue
# (reference QueueNameAnnotationKey, podgroups/mutate)
QUEUE_NAME_NAMESPACE_ANNOTATION = "volcano-tpu.io/queue-name"


def mutate_podgroup(pg, cluster=None):
    """Create-path defaulting (reference admission/podgroups/mutate):
    a podgroup left on the default queue adopts its NAMESPACE's
    queue-name annotation, so teams get per-namespace queues without
    every submitter naming one."""
    if pg.queue in ("", DEFAULT_QUEUE) and cluster is not None:
        ns_ann = getattr(cluster, "namespaces", {}).get(
            pg.namespace) or {}
        ns_queue = ns_ann.get(QUEUE_NAME_NAMESPACE_ANNOTATION)
        if ns_queue:
            pg.queue = ns_queue
    if not pg.queue:
        pg.queue = DEFAULT_QUEUE
    return pg


def validate_podgroup(pg) -> None:
    if pg.min_member < 0:
        raise AdmissionError("minMember must be >= 0")
    if pg.min_task_member:
        for name, n in pg.min_task_member.items():
            if n < 0:
                raise AdmissionError(f"minTaskMember[{name}] must be >= 0")


def validate_hypernode(hn) -> None:
    if hn.tier < 1:
        raise AdmissionError("hypernode tier must be >= 1")
    if not hn.members:
        raise AdmissionError("hypernode must have members")
    for m in hn.members:
        if m.kind not in ("Node", "HyperNode"):
            raise AdmissionError(f"invalid member kind {m.kind!r}")
        if not (m.exact or m.regex or m.labels):
            raise AdmissionError("member selector must be set")


# -- pods (reference admission/pods/{validate,mutate}) ----------------

# disruption-budget annotations (JDBMinAvailable/JDBMaxUnavailable
# analogues; consumed by plugins/pdb.py)
PDB_MIN_AVAILABLE_ANNOTATION = "volcano-tpu.io/min-available"
PDB_MAX_UNAVAILABLE_ANNOTATION = "volcano-tpu.io/max-unavailable"
# opt-in for the queue-admission scheduling gate (pods/mutate)
GATE_OPT_IN_ANNOTATION = "volcano-tpu.io/queue-admission-gate"


def _validate_int_or_percentage(key: str, value: str) -> None:
    """Positive integer, or '1%'..'99%' (admit_pod.go
    validateIntPercentageStr)."""
    s = str(value).strip()
    if s.endswith("%"):
        try:
            v = int(s[:-1])
        except ValueError:
            raise AdmissionError(
                f"invalid value {value!r} for {key}") from None
        if not 0 < v < 100:
            raise AdmissionError(
                f"invalid value {value!r} for {key}: percentage must be "
                f"between 1% and 99%")
        return
    try:
        v = int(s)
    except ValueError:
        raise AdmissionError(
            f"invalid value {value!r} for {key}: neither int nor "
            f"percentage") from None
    if v <= 0:
        raise AdmissionError(
            f"invalid value {value!r} for {key}: must be a positive "
            f"integer")


def validate_pod(pod) -> None:
    """Budget-annotation sanity for scheduler-managed pods
    (admit_pod.go:99-141): each must be int-or-percentage, and the two
    keys are mutually exclusive."""
    if pod.scheduler_name not in ("volcano-tpu", "volcano-tpu-agent"):
        return
    present = 0
    for key in (PDB_MIN_AVAILABLE_ANNOTATION,
                PDB_MAX_UNAVAILABLE_ANNOTATION):
        value = pod.annotations.get(key)
        if value is not None:
            present += 1
            _validate_int_or_percentage(key, value)
    if present > 1:
        raise AdmissionError(
            f"not allowed to configure both "
            f"{PDB_MIN_AVAILABLE_ANNOTATION} and "
            f"{PDB_MAX_UNAVAILABLE_ANNOTATION}")


def mutate_pod(pod):
    """Add the queue-admission scheduling gate for opted-in pods when
    the feature gate is on (mutate_pod.go:156-180; idempotent)."""
    from volcano_tpu import features
    if features.enabled("SchedulingGatesQueueAdmission") and \
            pod.annotations.get(GATE_OPT_IN_ANNOTATION) == "enable":
        from volcano_tpu.framework.job_updater import QUEUE_ADMISSION_GATE
        if QUEUE_ADMISSION_GATE not in pod.scheduling_gates:
            pod.scheduling_gates.append(QUEUE_ADMISSION_GATE)
    return pod


# -- jobflows (reference admission/jobflows/validate) -----------------

def validate_jobflow(flow) -> None:
    """DAG sanity: DNS names, unique steps, known dependency targets,
    no cycles (validate_jobflow.go:94)."""
    if not DNS1123.match(flow.name) or len(flow.name) > MAX_NAME_LEN:
        raise AdmissionError(f"jobflow name {flow.name!r} invalid")
    names = [s.name for s in flow.flows]
    if len(set(names)) != len(names):
        raise AdmissionError(f"duplicate flow steps: {names}")
    known = set(names)
    deps = {}
    for step in flow.flows:
        if not DNS1123.match(step.name):
            raise AdmissionError(f"flow step name {step.name!r} invalid")
        targets = step.depends_on.targets if step.depends_on else []
        for t in targets:
            if t not in known:
                raise AdmissionError(
                    f"flow step {step.name!r} depends on unknown "
                    f"target {t!r}")
        deps[step.name] = list(targets)
    # cycle detection (iterative DFS, 3-color)
    state: dict = {}

    def visit(n):
        stack = [(n, iter(deps.get(n, ())))]
        state[n] = 1
        while stack:
            cur, it = stack[-1]
            for nxt in it:
                if state.get(nxt) == 1:
                    raise AdmissionError(
                        f"jobflow DAG cycle through {nxt!r}")
                if nxt not in state:
                    state[nxt] = 1
                    stack.append((nxt, iter(deps.get(nxt, ()))))
                    break
            else:
                state[cur] = 2
                stack.pop()

    for n in deps:
        if n not in state:
            visit(n)


# -- cronjobs (reference admission/cronjobs/validate) -----------------

def validate_cronjob(cron, cluster=None) -> None:
    from volcano_tpu.controllers.cronjob import cron_field_valid

    if not DNS1123.match(cron.name) or len(cron.name) > MAX_NAME_LEN:
        raise AdmissionError(f"cronjob name {cron.name!r} invalid")
    fields = (cron.schedule or "").split()
    if len(fields) != 5:
        raise AdmissionError(
            f"schedule {cron.schedule!r} must have 5 cron fields")
    bounds = ((0, 59), (0, 23), (1, 31), (1, 12), (0, 6))
    for spec, (lo, hi) in zip(fields, bounds):
        if not cron_field_valid(spec, lo, hi):
            raise AdmissionError(
                f"invalid cron field {spec!r} in {cron.schedule!r}")
    if cron.concurrency_policy not in ("Allow", "Forbid", "Replace"):
        raise AdmissionError(
            f"invalid concurrencyPolicy {cron.concurrency_policy!r}")
    if cron.successful_jobs_history_limit < 0:
        raise AdmissionError("successfulJobsHistoryLimit must be >= 0")
    if cron.job_template is not None:
        job = mutate_job(cron.job_template)
        validate_job(job, cluster)


class AdmissionChain:
    """The webhook pipeline a Cluster applies on create (and the
    validate-only half on spec updates)."""

    def admit_job(self, job: VCJob, cluster=None) -> VCJob:
        job = mutate_job(job)
        validate_job(job, cluster)
        return job

    def admit_job_update(self, job: VCJob, cluster=None) -> VCJob:
        """Update path: spec sanity re-validated, but create-only gates
        (queue open/exists) are NOT re-applied — a controller flushing
        status on a job whose queue has since closed must not be
        rejected."""
        validate_job(job, cluster=None)
        return job

    def admit_queue(self, queue, cluster=None):
        queue = mutate_queue(queue)
        validate_queue(queue, cluster)
        return queue

    def admit_podgroup(self, pg, cluster=None):
        pg = mutate_podgroup(pg, cluster)
        validate_podgroup(pg)
        return pg

    def admit_hypernode(self, hn, cluster=None):
        validate_hypernode(hn)
        return hn

    def admit_pod(self, pod, cluster=None):
        pod = mutate_pod(pod)
        validate_pod(pod)
        return pod

    def admit_jobflow(self, flow, cluster=None):
        validate_jobflow(flow)
        return flow

    def admit_cronjob(self, cron, cluster=None):
        validate_cronjob(cron, cluster)
        return cron


def default_admission() -> AdmissionChain:
    return AdmissionChain()
