"""Standalone webhook-manager process — the vc-webhook-manager binary.

Reference parity: cmd/webhook-manager + pkg/webhooks/router
(admission.go:35).  In the reference, admission runs as its OWN
deployment: the apiserver calls out to it over HTTPS for every create.
Here the state server does the same when started with --webhook-url:
instead of running the embedded chain, it POSTs the object to this
process's /admit route and stores whatever comes back (mutations
included), rejecting on a webhook veto.

The webhook process holds its own read-only LIST+WATCH mirror of the
state server (a RemoteCluster), the analogue of the reference
webhooks' informer-backed listers — cross-object checks (queue
exists/open, hierarchy cycles) read the mirror, never call back into
the serving request.

Run:  volcano-tpu-webhook --cluster-url http://HOST:PORT --port 7443
"""

from __future__ import annotations

import argparse
import json
import logging
import threading
from http.server import BaseHTTPRequestHandler

from volcano_tpu.api import codec
from volcano_tpu.server.httputil import json_response, serve_threaded
from volcano_tpu.webhooks.admission import AdmissionError, default_admission

log = logging.getLogger(__name__)

ADMIT_METHODS = frozenset({
    "admit_job", "admit_job_update", "admit_queue", "admit_podgroup",
    "admit_hypernode", "admit_pod", "admit_jobflow", "admit_cronjob",
})


class WebhookServer:
    """Admission chain + a read-only cluster mirror for cross-object
    validation."""

    def __init__(self, cluster=None):
        self.chain = default_admission()
        self.cluster = cluster          # RemoteCluster mirror or None

    def admit(self, method: str, obj):
        if method not in ADMIT_METHODS:
            raise AdmissionError(f"unknown admission method {method!r}")
        return getattr(self.chain, method)(obj, self.cluster)


class _Handler(BaseHTTPRequestHandler):
    server_version = "volcano-tpu-webhook"
    protocol_version = "HTTP/1.1"
    hooks: WebhookServer = None          # injected by serve_webhooks()
    token: str = ""                      # bearer token for /admit

    def _json(self, code: int, payload: dict):
        json_response(self, code, payload)

    def do_GET(self):  # noqa: N802
        if self.path == "/healthz":
            return self._json(200, {"ok": True})
        return self._json(404, {"error": f"no route {self.path}"})

    def do_POST(self):  # noqa: N802
        from volcano_tpu.server.tlsutil import token_ok
        if not token_ok(self.token, self.headers.get("Authorization")):
            return self._json(401, {"error": "missing or invalid "
                                             "bearer token"})
        if self.path != "/admit":
            return self._json(404, {"error": f"no route {self.path}"})
        try:
            length = int(self.headers.get("Content-Length", "0"))
            payload = json.loads(self.rfile.read(length))
            obj = codec.decode(payload["obj"])
            out = self.hooks.admit(payload["method"], obj)
            return self._json(200, {"ok": True,
                                    "obj": codec.encode(out)})
        except AdmissionError as e:
            return self._json(200, {"ok": False, "error": str(e)})
        except Exception as e:  # noqa: BLE001 - malformed request
            log.exception("webhook request failed")
            return self._json(400, {"ok": False, "error": str(e)})

    def log_message(self, *args):  # quiet
        pass


def serve_webhooks(port: int = 0, cluster=None, tls_cert: str = "",
                   tls_key: str = "", token: str = ""):
    """Start the webhook HTTP server (daemon thread); returns httpd."""
    return serve_threaded(_Handler, {"hooks": WebhookServer(cluster),
                                     "token": token},
                          port, "webhook-server",
                          tls_cert=tls_cert, tls_key=tls_key)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="volcano-tpu-webhook")
    parser.add_argument("--port", type=int, default=7443)
    parser.add_argument("--cluster-url", default="",
                        help="state server to mirror for cross-object "
                             "validation (informer-lister analogue)")
    parser.add_argument("--tls-cert", default="",
                        help="serve TLS with this certificate (PEM)")
    parser.add_argument("--tls-key", default="")
    parser.add_argument("--token", default="",
                        help="cluster bearer token: required of "
                             "callers of /admit AND presented to the "
                             "state server")
    parser.add_argument("--token-file", default="")
    parser.add_argument("--ca-cert", default="",
                        help="CA bundle for the state-server mirror")
    parser.add_argument("--insecure", action="store_true",
                        help="skip state-server cert verification")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s")
    from volcano_tpu.server.tlsutil import load_token
    token = load_token(args.token, args.token_file)
    cluster = None
    if args.cluster_url:
        from volcano_tpu.cache.remote_cluster import RemoteCluster
        cluster = RemoteCluster(args.cluster_url, token=token,
                                ca_cert=args.ca_cert,
                                insecure=args.insecure)
    httpd = serve_webhooks(args.port, cluster,
                           tls_cert=args.tls_cert,
                           tls_key=args.tls_key, token=token)
    log.info("webhook manager listening on :%d",
             httpd.server_address[1])
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.shutdown()
        if cluster is not None:
            cluster.close()
    return 0


class RemoteAdmission:
    """Admission proxy the STATE SERVER uses when --webhook-url is set:
    every create/update POSTs to the external webhook manager, exactly
    like the apiserver calling a registered webhook.

    failure_policy: "Fail" rejects when the webhook is unreachable
    (the reference default), "Ignore" admits unvalidated.
    """

    # class-level defaults so instances unpickled from PRE-auth state
    # files resolve these without tripping __getattr__
    token = ""
    _tls = ("", False)

    def __init__(self, url: str, timeout: float = 5.0,
                 failure_policy: str = "Fail", token: str = "",
                 ca_cert: str = "", insecure: bool = False):
        self.url = url.rstrip("/")
        self.timeout = timeout
        self.failure_policy = failure_policy
        self.token = token
        self._tls = (ca_cert, insecure)

    # the ssl context is rebuilt after unpickling (state files may
    # carry a RemoteAdmission; contexts don't pickle)
    @property
    def _ssl_ctx(self):
        ctx = self.__dict__.get("_ssl_ctx_cached")
        if ctx is None and any(getattr(self, "_tls", ("", False))):
            from volcano_tpu.server.tlsutil import client_ssl_context
            ctx = client_ssl_context(*self._tls)
            self.__dict__["_ssl_ctx_cached"] = ctx
        return ctx

    def __getstate__(self):
        state = self.__dict__.copy()
        state.pop("_ssl_ctx_cached", None)
        return state

    def _call(self, method: str, obj, cluster=None):
        import urllib.request
        del cluster   # the webhook process uses its own mirror
        body = json.dumps({"method": method,
                           "obj": codec.encode(obj)}).encode()
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        req = urllib.request.Request(
            self.url + "/admit", data=body, method="POST",
            headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout,
                                        context=self._ssl_ctx) as resp:
                payload = json.loads(resp.read())
        except Exception as e:  # noqa: BLE001 - webhook down/unreachable
            if self.failure_policy == "Ignore":
                log.warning("webhook %s unreachable (%s); admitting "
                            "per failurePolicy=Ignore", self.url, e)
                return obj
            raise AdmissionError(
                f"admission webhook unreachable: {e}") from None
        if not payload.get("ok"):
            raise AdmissionError(payload.get("error", "webhook denied"))
        return codec.decode(payload["obj"])

    def __getattr__(self, name: str):
        if name in ADMIT_METHODS:
            return lambda obj, cluster=None: self._call(name, obj,
                                                        cluster)
        raise AttributeError(name)


if __name__ == "__main__":
    raise SystemExit(main())
