"""Admission webhooks (reference: pkg/webhooks)."""

from volcano_tpu.webhooks.admission import (
    AdmissionChain, AdmissionError, default_admission,
)

__all__ = ["AdmissionChain", "AdmissionError", "default_admission"]
