"""Static snapshot-immutability pass — the `go vet` half of race
certification for the parallel scheduler cycle (the runtime half is
analysis/freezeaudit.py).

ROADMAP item 3 fans the predicate/scoring sweep out across a pool
over a read-only snapshot.  That is only sound if every function the
sweep can reach treats the session snapshot as immutable.  This pass
makes the claim mechanical:

  1. OWNERSHIP / REACHABILITY — functions registered at the reader
     extension points (``add_predicate_fn``/``add_pre_predicate_fn``/
     ``add_node_order_fn``/``add_batch_node_order_fn``/
     ``add_grouped_batch_node_order_fn``/``add_hyper_node_order_fn``,
     or the equivalent ``add_fn("predicate", ...)`` form), plus the
     sweep machinery itself (``fit_class``/``predicate_nodes``/
     ``split_by_fit``/``prioritize_nodes``/``sweep_shard``/
     ``SpecCache.build_entry`` and the Session dispatchers), are
     classified *snapshot-readers*; classification propagates through
     the call graph by conservative name matching, STOPPING at the
     designated mutation seams (Session's five state primitives +
     ``set_job_pending_reason``, the Statement paths,
     ``record_fit_error``/``add_task``/``remove_task``/
     ``update_task_status``, ``SpecCache.invalidate``/``_admit``/
     ``_seal``) and at the locked sink modules (metrics/trace, whose
     internal order is serialized by their own locks and audited at
     runtime by lockaudit.guard_store).

  2. ``snapshot-write`` — inside a reader, any attribute/item write,
     delete, or known-mutator call (``add``/``sub``/``append``/
     ``pop``/``update``/``record_fit_error``/``heappush``/...) whose
     receiver chain roots at a snapshot object (a task/node/job/queue/
     session parameter, a local assigned from one, or ``self`` of a
     snapshot class) is flagged: under the fan-out that write races
     every concurrent reader of the same object.

  3. ``shared-cache-unkeyed`` — the same mutations rooted at PLUGIN
     or Session instance state (``self._cache[...] = ...``) or a
     module global: a memo shared across concurrent sweep calls
     without a serializing lock or per-sweep keying.

Waivers: the standard ``# vtplint: disable=<rule> (<reason>)`` form;
each reason must name the serializing lock or the single-threaded
phase that makes the write safe (docs/design/static-analysis.md).
Like every heuristic in this linter the pass over-approximates on
purpose — a reasoned waiver is the documented escape hatch, a missed
write is a 3am deadlock-free data corruption.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Set

from volcano_tpu.analysis.astlint import (Finding, _attr_chain,
                                          match_waivers)

RULES = ("snapshot-write", "shared-cache-unkeyed")

# reader registration seams: ssn.add_predicate_fn(name, fn) etc.
READER_REG = frozenset({
    "add_predicate_fn", "add_pre_predicate_fn", "add_node_order_fn",
    "add_batch_node_order_fn", "add_grouped_batch_node_order_fn",
    "add_hyper_node_order_fn",
})
# ...and the add_fn("point", name, fn) spelling
READER_POINTS = frozenset({
    "predicate", "prePredicate", "nodeOrder", "batchNodeOrder",
    "groupedBatchNodeOrder", "hyperNodeOrder",
})

# sweep machinery roots by bare name / qualname
ROOT_NAMES = frozenset({
    "fit_class", "predicate_nodes", "split_by_fit",
    "prioritize_nodes", "sweep_shard",
})
ROOT_QUALS = frozenset({
    "SpecCache.build_entry", "SpecCache._build_serial",
    "SpecCache._build_parallel",
    "Session.predicate", "Session.predicate_for_preempt",
    "Session._run_predicates", "Session.pre_predicate",
    "Session.node_order", "Session.batch_node_order",
    "Session.grouped_batch_node_order", "Session.hyper_node_order",
})

# the designated mutation seams: reachability stops here, and a
# rooted mutating CALL to one of these from a reader is reported at
# the call site (record_fit_error below)
SEAM_QUALS = frozenset({
    "Session.allocate", "Session.pipeline", "Session.evict",
    "Session.deallocate", "Session.unevict",
    "Session.set_job_pending_reason",
    "Statement.allocate", "Statement.pipeline", "Statement.evict",
    "Statement.commit", "Statement.discard", "Statement.rollback_to",
    "Statement.recover_operations",
    "JobInfo.record_fit_error", "JobInfo.set_job_fit_errors",
    "JobInfo.update_task_status",
    "NodeInfo.add_task", "NodeInfo.remove_task",
    "NodeInfo.update_task_status",
    "SpecCache.invalidate", "SpecCache._admit", "SpecCache._seal",
    "SpecCache._new_entry",
})

# locked sinks: modules whose internal mutation is serialized by
# their own lock (metrics._lock / trace._lock), runtime-audited by
# lockaudit.guard_store — reachability does not descend into them
SINK_MODULES = ("volcano_tpu/metrics.py", "volcano_tpu/trace.py")

# The ownership domain: the scheduler-cycle code the parallel sweep
# can actually reach.  The agent's own scheduler, the state server,
# controllers, CLI and workloads run in other processes/threads with
# their own locking stories (lockaudit's beat) — including them here
# would only drown the sweep findings in same-name noise.
DOMAIN = (
    "volcano_tpu/actions", "volcano_tpu/plugins",
    "volcano_tpu/framework", "volcano_tpu/api",
    "volcano_tpu/util.py", "volcano_tpu/goodput.py",
    "volcano_tpu/conf.py", "volcano_tpu/metrics.py",
    "volcano_tpu/trace.py",
)


def in_domain(rel: str) -> bool:
    rel = rel.replace("\\", "/")
    for d in DOMAIN:
        if d.endswith(".py"):
            if rel.endswith(d):
                return True
        elif f"{d}/" in rel or rel.rstrip("/").endswith(d):
            return True
    return False

# parameter names that denote snapshot-reachable objects inside a
# reader (the framework's reader signatures use exactly these)
SNAPSHOT_PARAMS = frozenset({
    "task", "proto", "node", "nodes", "job", "jobs", "queue",
    "queues", "ssn", "session", "candidates", "candidate_nodes",
    "shard", "fit_nodes", "idle_fit", "future_fit", "hypernodes",
    "hypernode", "hn", "sub", "sub_job", "subjob", "preemptor",
    "reclaimer", "victim", "victims", "entry", "taskinfo",
    "task_info", "node_info",
})
# `self` of these classes is snapshot data (a write through self is a
# snapshot-write, not a cache write)
SNAPSHOT_CLASSES = frozenset({
    "NodeInfo", "JobInfo", "TaskInfo", "SubJobInfo", "QueueInfo",
    "HyperNode", "HyperNodeInfo", "HyperNodesInfo",
})

# receiver methods whose return value ALIASES stored state (rooted
# in, rooted out); any other call breaks the chain — e.g. clone()
# and future_idle() return fresh objects, so they are NOT here
ALIAS_CALLS = frozenset({
    "get", "values", "items", "keys", "tasks_in_status",
    "leaf_of_node", "hypernodes_covering", "members_of",
})

# known mutating methods: a rooted receiver makes the call a finding
MUTATORS = frozenset({
    "add", "sub", "sub_unchecked", "set_scalar",
    "append", "extend", "insert", "remove", "discard", "pop",
    "popitem", "clear", "update", "setdefault", "move_to_end",
    "add_task", "remove_task", "update_task_status",
    "record_fit_error", "set_error", "set_node_error",
})
HEAP_FNS = frozenset({"heappush", "heappop", "heapify",
                      "heappushpop", "heapreplace"})


class FuncInfo:
    __slots__ = ("name", "qual", "cls", "path", "node", "is_reader")

    def __init__(self, name, qual, cls, path, node):
        self.name = name
        self.qual = qual
        self.cls = cls
        self.path = path
        self.node = node
        self.is_reader = False


class Program:
    """A set of parsed sources analyzed as one ownership domain."""

    def __init__(self) -> None:
        self.sources: Dict[str, str] = {}
        self.trees: Dict[str, ast.Module] = {}
        self.funcs: List[FuncInfo] = []
        self.by_name: Dict[str, List[FuncInfo]] = {}
        self.by_qual: Dict[str, List[FuncInfo]] = {}
        self.parse_errors: List[Finding] = []

    # -- loading -------------------------------------------------------

    def add_source(self, path: str, src: str) -> None:
        rel = path.replace("\\", "/")
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            self.parse_errors.append(Finding(
                "syntax-error", rel, e.lineno or 0,
                f"cannot parse: {e.msg}"))
            return
        self.sources[rel] = src
        self.trees[rel] = tree
        self._index(rel, tree)

    def _index(self, rel: str, tree: ast.Module) -> None:
        def walk(node, cls: Optional[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    walk(child, child.name)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    qual = f"{cls}.{child.name}" if cls else child.name
                    info = FuncInfo(child.name, qual, cls, rel, child)
                    self.funcs.append(info)
                    self.by_name.setdefault(child.name, []).append(info)
                    self.by_qual.setdefault(qual, []).append(info)
                    walk(child, cls)   # nested defs keep class ctx
                else:
                    walk(child, cls)

        walk(tree, None)

    # -- roots ---------------------------------------------------------

    def _roots(self) -> List[FuncInfo]:
        roots: List[FuncInfo] = []
        for name in ROOT_NAMES:
            roots.extend(self.by_name.get(name, ()))
        for qual in ROOT_QUALS:
            roots.extend(self.by_qual.get(qual, ()))
        # registration sites: ssn.add_predicate_fn(self.name, self._fn)
        for rel, tree in self.trees.items():
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                attr = _attr_chain(node.func).rsplit(".", 1)[-1]
                fn_arg = None
                if attr in READER_REG and len(node.args) >= 2:
                    fn_arg = node.args[1]
                elif attr == "add_fn" and len(node.args) >= 3:
                    point = node.args[0]
                    if isinstance(point, ast.Constant) and \
                            point.value in READER_POINTS:
                        fn_arg = node.args[2]
                if fn_arg is None:
                    continue
                fn_name = None
                if isinstance(fn_arg, ast.Attribute):
                    fn_name = fn_arg.attr
                elif isinstance(fn_arg, ast.Name):
                    fn_name = fn_arg.id
                if not fn_name:
                    continue
                cands = [f for f in self.by_name.get(fn_name, ())
                         if f.path == rel] or \
                    self.by_name.get(fn_name, [])
                roots.extend(cands)
        return roots

    # -- reachability --------------------------------------------------

    def classify(self) -> None:
        work = list(self._roots())
        while work:
            fn = work.pop()
            if fn.is_reader:
                continue
            if fn.qual in SEAM_QUALS or fn.path.endswith(SINK_MODULES):
                continue
            fn.is_reader = True
            for callee in self._callees(fn):
                if not callee.is_reader:
                    work.append(callee)

    def _callees(self, fn: FuncInfo) -> Iterable[FuncInfo]:
        seen: Set[int] = set()
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            # calls named like known mutators/aliases are judged at
            # the CALL SITE (rooted receiver => finding); descending
            # into every same-named def (dict.pop vs PriorityQueue.pop
            # vs Resource.add) only manufactures unrelated readers
            name = func.attr if isinstance(func, ast.Attribute) else \
                getattr(func, "id", "")
            if name in MUTATORS or name in ALIAS_CALLS or \
                    name in HEAP_FNS or name in ("push", "pop"):
                continue
            targets: List[FuncInfo] = []
            if isinstance(func, ast.Name):
                # plain call: resolve against known defs anywhere
                targets = self.by_name.get(func.id, [])
            elif isinstance(func, ast.Attribute):
                # method call: resolve by bare name.  self.X prefers
                # the same class; Class.X (capitalized receiver)
                # resolves to that class
                cands = self.by_name.get(func.attr, [])
                base = func.value
                if isinstance(base, ast.Name) and base.id == "self" \
                        and fn.cls:
                    same = [c for c in cands if c.cls == fn.cls]
                    targets = same or cands
                elif isinstance(base, ast.Name) and base.id[:1].isupper():
                    targets = [c for c in cands if c.cls == base.id] \
                        or cands
                else:
                    targets = cands
            for t in targets:
                if id(t) not in seen:
                    seen.add(id(t))
                    yield t

    # -- per-reader mutation scan -------------------------------------

    def analyze(self) -> List[Finding]:
        self.classify()
        raw: Dict[str, List[Finding]] = {}
        for fn in self.funcs:
            if not fn.is_reader:
                continue
            if fn.qual in SEAM_QUALS or fn.path.endswith(SINK_MODULES):
                continue
            for f in _scan_reader(fn):
                raw.setdefault(fn.path, []).append(f)
        findings: List[Finding] = list(self.parse_errors)
        for rel, fs in raw.items():
            findings.extend(match_waivers(fs, self.sources[rel], rel))
        return findings

    def readers(self) -> List[str]:
        """The classified reader set (for reports/debugging)."""
        return sorted({f"{f.path}:{f.qual}" for f in self.funcs
                       if f.is_reader})


def _param_names(node) -> List[str]:
    a = node.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def _scan_reader(fn: FuncInfo) -> List[Finding]:
    """Taint params -> locals, then flag rooted mutations."""
    rooted: Dict[str, str] = {}      # name -> "snap" | "shared"
    for p in _param_names(fn.node):
        if p in SNAPSHOT_PARAMS:
            rooted[p] = "snap"
        elif p == "self":
            rooted[p] = "snap" if fn.cls in SNAPSHOT_CLASSES \
                else "shared"

    def chain_kind(expr) -> Optional[str]:
        """Root kind of an expression chain, or None (fresh/local)."""
        while True:
            if isinstance(expr, ast.Name):
                return rooted.get(expr.id)
            if isinstance(expr, ast.Attribute):
                expr = expr.value
                continue
            if isinstance(expr, ast.Subscript):
                expr = expr.value
                continue
            if isinstance(expr, ast.Call):
                f = expr.func
                if isinstance(f, ast.Attribute) and \
                        f.attr in ALIAS_CALLS:
                    expr = f.value
                    continue
                return None
            return None

    # forward taint to fixpoint: x = <rooted>, for x in <rooted>,
    # with <rooted> as x
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn.node):
            tgt = None
            src = None
            if isinstance(node, ast.Assign) and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                tgt, src = node.targets[0].id, node.value
            elif isinstance(node, ast.For) and \
                    isinstance(node.target, ast.Name):
                tgt, src = node.target.id, node.iter
            elif isinstance(node, ast.withitem) and \
                    node.optional_vars is not None and \
                    isinstance(node.optional_vars, ast.Name):
                tgt, src = node.optional_vars.id, node.context_expr
            if tgt is None or tgt in rooted:
                continue
            kind = chain_kind(src)
            if kind is not None:
                rooted[tgt] = kind
                changed = True

    findings: List[Finding] = []

    def flag(kind: str, line: int, what: str) -> None:
        if kind == "snap":
            findings.append(Finding(
                "snapshot-write", fn.path, line,
                f"{fn.qual}: {what} mutates snapshot-reachable state "
                f"inside a snapshot-reader — under the parallel sweep "
                f"this write races every concurrent reader; move it "
                f"behind a mutation seam or waive with the "
                f"serializing lock/phase"))
        else:
            findings.append(Finding(
                "shared-cache-unkeyed", fn.path, line,
                f"{fn.qual}: {what} mutates shared instance/module "
                f"state inside a snapshot-reader — concurrent sweep "
                f"calls share this cache unsynchronized; key it per "
                f"sweep, guard it, or waive with the lock/phase"))

    def render(expr) -> str:
        try:
            return ast.unparse(expr)
        except Exception:  # noqa: BLE001 — unparse is best-effort
            return "<expr>"

    for node in ast.walk(fn.node):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)):
                    kind = chain_kind(t.value)
                    if kind is not None:
                        flag(kind, node.lineno,
                             f"assignment to {render(t)}")
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)):
                    kind = chain_kind(t.value)
                    if kind is not None:
                        flag(kind, node.lineno, f"del {render(t)}")
        elif isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute):
                name = func.attr
                if name in MUTATORS:
                    kind = chain_kind(func.value)
                    if kind is not None:
                        flag(kind, node.lineno,
                             f"{render(func)}(...) [known mutator]")
                elif name in HEAP_FNS and node.args:
                    kind = chain_kind(node.args[0])
                    if kind is not None:
                        flag(kind, node.lineno,
                             f"heapq.{name} on {render(node.args[0])}")
            elif isinstance(func, ast.Name) and func.id in HEAP_FNS \
                    and node.args:
                kind = chain_kind(node.args[0])
                if kind is not None:
                    flag(kind, node.lineno,
                         f"{func.id} on {render(node.args[0])}")
    return findings


# -- entry points -----------------------------------------------------

def build_program(paths) -> Program:
    prog = Program()
    for path in paths:
        if os.path.isfile(path):
            if in_domain(path):
                with open(path, encoding="utf-8") as f:
                    prog.add_source(path, f.read())
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            for fname in sorted(files):
                if not fname.endswith(".py"):
                    continue
                fpath = os.path.join(root, fname)
                if not in_domain(fpath):
                    continue
                with open(fpath, encoding="utf-8") as f:
                    prog.add_source(fpath, f.read())
    return prog


def check_paths(paths) -> List[Finding]:
    """Analyze every .py under *paths* as one ownership domain."""
    return build_program(paths).analyze()


def check_sources(sources: Dict[str, str]) -> List[Finding]:
    """Analyze an in-memory file set (the broken-fixture tests)."""
    prog = Program()
    for path, src in sources.items():
        prog.add_source(path, src)
    return prog.analyze()
