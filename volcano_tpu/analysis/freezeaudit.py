"""Opt-in runtime snapshot-freeze + data-race auditor — the
``go test -race`` half of what vtplint's static ownership pass
(analysis/racecheck.py) proves, in the lockaudit.py mold.

Armed by ``VTP_RACE_AUDIT=1`` (installed from ``volcano_tpu/__init__``
before any session exists) or ``install()`` from a test.  Three
mechanisms, one report:

  frozen-write     ``open_session`` deep-freezes the session snapshot:
                   NodeInfo/JobInfo/TaskInfo/SubJobInfo/QueueInfo
                   instances (plus their Resource accounting objects
                   and hot dicts) get ``__setattr__``/``__setitem__``
                   write barriers.  Until the session's FIRST
                   Statement commit, any write outside a designated
                   mutation seam is a violation; after the first
                   commit the single-threaded window closes and only
                   the two always-on conditions below keep firing —
                   a write from a thread other than the session
                   owner, or any write while a fan-out region is
                   active.
  seam-in-fanout   the designated seams (Session.allocate/pipeline/
                   evict/deallocate/unevict/set_job_pending_reason,
                   Statement.commit/rollback, JobInfo.record_fit_error)
                   are the ONLY sanctioned writers, and they are
                   single-threaded by contract: entering one while a
                   parallel sweep is in flight is itself a violation,
                   whatever it writes.
  unsync-pair      ThreadSanitizer-lite for tracked shared stores: a
                   ``TrackedDict`` records (thread, op, held-lock set,
                   site) per access — the held set comes from the lock
                   auditor's per-thread graph when it is armed — and
                   ``report()`` derives every cross-thread pair with a
                   write on one side and NO common lock between the
                   two held sets.  Armed processes track the stores
                   whose race waivers claim owner-thread CONFINEMENT
                   (``SpecCache.entries`` and the Session dispatch
                   memos, wired in ``maybe_freeze_session`` /
                   ``SpecCache.__init__``); see ``track()`` for why
                   the GIL-publish plugin memos are excluded.

Reports flush to ``VTP_RACE_AUDIT_OUT`` at 2Hz, at exit and on
SIGTERM (same contract as lockaudit: a SIGKILL'd chaos incarnation
still leaves its last report on disk); the chaos conductor's
``--race-audit`` merges them across the process plane.
"""

from __future__ import annotations

import os
import sys
import threading
import traceback
from typing import Dict, List, Optional

ENV_FLAG = "VTP_RACE_AUDIT"
ENV_OUT = "VTP_RACE_AUDIT_OUT"

_ACTIVE = False
_PATCHED = False
_REG_LOCK = threading.Lock()
_TL = threading.local()

# id(obj) -> session uid that froze it
_FROZEN: Dict[int, str] = {}
# session uid -> {"owner": tid, "committed": bool, "objects": [ids]}
_SESSIONS: Dict[str, dict] = {}
_FANOUT = {"depth": 0, "owner": None}
_VIOLATIONS: List[dict] = []
_SEEN: set = set()
_TRACKS: Dict[str, list] = {}        # store name -> access rows
_COUNTS = {"frozen_objects": 0, "fanout_regions": 0, "sessions": 0}

_TRACK_CAP = 256                     # bounded per-store access log


def _stack(skip: int = 3) -> str:
    frames = traceback.extract_stack()[:-skip]
    keep = [f for f in frames
            if "freezeaudit" not in f.filename][-8:]
    return "".join(traceback.format_list(keep)).rstrip()


def _site(depth: int = 2) -> str:
    """file:line of the nearest caller frame outside this module."""
    try:
        frame = sys._getframe(depth)
        while frame is not None and \
                frame.f_code.co_filename.endswith("freezeaudit.py"):
            frame = frame.f_back
    except ValueError:
        return "?"
    if frame is None:
        return "?"
    return f"{os.path.basename(frame.f_code.co_filename)}:" \
           f"{frame.f_lineno}"


def _violation(kind: str, key, **fields) -> None:
    with _REG_LOCK:
        if key in _SEEN:
            return
        _SEEN.add(key)
        doc = {"kind": kind, "thread": threading.current_thread().name}
        doc.update(fields)
        _VIOLATIONS.append(doc)


def record_boundary_violation(kind: str, key, **fields) -> None:
    """Public entry for audits that live OUTSIDE this module but
    report through it — the process-pool's per-worker mirror
    divergence check (actions/procpool.py) records here so conductor
    runs and race_bench fail on exactly the same report surface as
    in-process freeze violations.  No-op when disarmed."""
    if not _ACTIVE:
        return
    _violation(kind, key, **fields)


def _held_names() -> frozenset:
    """The acquiring thread's held-lock names from the lock auditor
    (empty when it is not armed — pairs then need no common lock to
    fire, which is exactly the conservative reading)."""
    from volcano_tpu.analysis import lockaudit
    if not lockaudit.enabled():
        return frozenset()
    return frozenset(h.name for h in lockaudit._held())


# -- seams ------------------------------------------------------------

class _Seam:
    """Context manager marking a designated mutation seam.  Reentrant
    per thread; entering one while a fan-out region is active is a
    violation (the seams are the single-threaded writers the parallel
    sweep must never overlap)."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        depth = getattr(_TL, "seam", 0)
        _TL.seam = depth + 1
        if _ACTIVE and _FANOUT["depth"]:
            _violation(
                "seam-in-fanout",
                ("seam-in-fanout", self.name, _site(2)),
                seam=self.name, site=_site(2), stack=_stack())
        return self

    def __exit__(self, *exc):
        _TL.seam = getattr(_TL, "seam", 1) - 1
        return False


def in_seam() -> bool:
    return getattr(_TL, "seam", 0) > 0


# -- write barriers ---------------------------------------------------

def _check_write(obj, what: str, detail: str) -> None:
    if not _ACTIVE:
        return
    sid = _FROZEN.get(id(obj))
    if sid is None:
        return
    if in_seam():
        return        # seam legality is checked at seam entry
    sess = _SESSIONS.get(sid)
    if sess is None:
        return
    tid = threading.get_ident()
    if _FANOUT["depth"]:
        why = "written while a parallel sweep is in flight"
    elif tid != sess["owner"]:
        why = "written from a non-owner thread"
    elif not sess["committed"]:
        why = "written outside a mutation seam before the session's " \
              "first Statement commit"
    else:
        return
    site = _site()
    _violation(
        "frozen-write",
        ("frozen-write", type(obj).__name__, detail, site),
        object=type(obj).__name__, target=detail, op=what,
        session=sid, site=site, reason=why, stack=_stack())


def _guard_setattr(cls) -> None:
    orig = cls.__dict__.get("__setattr__", None) or cls.__setattr__
    if getattr(orig, "_vtp_freeze", False):
        return

    def guarded(self, name, value, _orig=orig):
        _check_write(self, "setattr", f"{type(self).__name__}.{name}")
        _orig(self, name, value)

    guarded._vtp_freeze = True
    guarded._vtp_orig = orig
    cls.__setattr__ = guarded


def _guard_method(cls, method: str) -> None:
    orig = cls.__dict__.get(method)
    if orig is None or getattr(orig, "_vtp_freeze", False):
        return

    def guarded(self, *a, _orig=orig, _m=method, **kw):
        _check_write(self, _m, f"{type(self).__name__}.{_m}()")
        return _orig(self, *a, **kw)

    guarded._vtp_freeze = True
    guarded._vtp_orig = orig
    setattr(cls, method, guarded)


class FrozenDict(dict):
    """dict with the freeze write barrier on every mutator — swapped
    in for the snapshot's hot dicts (ssn.nodes/jobs/queues,
    node.tasks, node.occupied_ports) while their session is frozen."""

    __slots__ = ("_vtp_name",)

    def __init__(self, data, name: str):
        super().__init__(data)
        self._vtp_name = name

    def __reduce__(self):
        # a pickled copy THAWS to a plain dict: the barrier guards
        # THIS process's snapshot objects, and the default dict-
        # subclass protocol would rebuild item-by-item through the
        # armed __setitem__ barrier on a half-constructed instance
        # (no _vtp_name yet) — which killed every process-pool mirror
        # worker that received a frozen owner's shipped payload.  The
        # worker freezes its OWN mirror session when armed.
        return (dict, (dict(self),))

    def _bar(self, op):
        _check_write(self, op, f"{self._vtp_name}[{op}]")

    def __setitem__(self, k, v):
        self._bar("__setitem__")
        super().__setitem__(k, v)

    def __delitem__(self, k):
        self._bar("__delitem__")
        super().__delitem__(k)

    def pop(self, *a, **kw):
        self._bar("pop")
        return super().pop(*a, **kw)

    def popitem(self):
        self._bar("popitem")
        return super().popitem()

    def clear(self):
        self._bar("clear")
        super().clear()

    def update(self, *a, **kw):
        self._bar("update")
        super().update(*a, **kw)

    def setdefault(self, *a, **kw):
        self._bar("setdefault")
        return super().setdefault(*a, **kw)


# -- cross-thread access tracking ------------------------------------

class TrackedDict(dict):
    """dict recording (thread, op, held-locks, site) per access, the
    raw material for unsync-pair detection.  Bounded and deduped by
    (thread, op, site) so hot read loops stay cheap."""

    __slots__ = ("_vtp_name", "_vtp_seen")

    def __init__(self, data, name: str):
        super().__init__(data)
        self._vtp_name = name
        self._vtp_seen = set()

    def _note(self, op: str):
        if not _ACTIVE:
            return
        tid = threading.get_ident()
        site = _site()
        key = (tid, op, site)
        if key in self._vtp_seen:
            return
        self._vtp_seen.add(key)
        with _REG_LOCK:
            rows = _TRACKS.setdefault(self._vtp_name, [])
            if len(rows) < _TRACK_CAP:
                rows.append({"tid": tid, "op": op, "site": site,
                             "held": _held_names(),
                             "stack": _stack()})

    def __getitem__(self, k):
        self._note("read")
        return super().__getitem__(k)

    def get(self, k, default=None):
        self._note("read")
        return super().get(k, default)

    def __contains__(self, k):
        self._note("read")
        return super().__contains__(k)

    def items(self):
        self._note("read")
        return super().items()

    def values(self):
        self._note("read")
        return super().values()

    def __setitem__(self, k, v):
        self._note("write")
        super().__setitem__(k, v)

    def __delitem__(self, k):
        self._note("write")
        super().__delitem__(k)

    def pop(self, *a, **kw):
        self._note("write")
        return super().pop(*a, **kw)

    def update(self, *a, **kw):
        self._note("write")
        super().update(*a, **kw)

    def setdefault(self, *a, **kw):
        self._note("write")
        return super().setdefault(*a, **kw)

    def clear(self):
        self._note("write")
        super().clear()


def track(data: dict, name: str) -> TrackedDict:
    """Wrap a shared store for cross-thread pair detection.

    Production wiring targets the CONFINEMENT claims the static
    pass's waivers make — stores argued safe because only the session
    owner thread ever touches them (``SpecCache.entries``, the
    Session dispatch memos): any cross-thread access on one of these
    is a pair regardless of held locks, so the detector needs no
    lock-auditor fidelity to be sound there.  The plugin memo caches
    are deliberately NOT tracked: their waivers argue idempotent
    GIL-atomic publish — pool workers DO legitimately race those, and
    tracking them would fire on the benign-by-argument pattern the
    waiver inventory exists to document."""
    if isinstance(data, TrackedDict):
        return data
    return TrackedDict(data, name)


def _unsync_pairs() -> List[dict]:
    """Cross-thread (write, any) pairs on a tracked store whose two
    held-lock sets share nothing: with no common lock there is no
    ordering, and the pair is a data race under the right schedule."""
    out = []
    seen = set()
    with _REG_LOCK:
        snapshot = {name: list(rows) for name, rows in _TRACKS.items()}
    for name, rows in snapshot.items():
        for a in rows:
            if a["op"] != "write":
                continue
            for b in rows:
                if b is a or b["tid"] == a["tid"]:
                    continue
                if a["held"] & b["held"]:
                    continue
                key = (name,) + tuple(sorted((a["site"], b["site"])))
                if key in seen:
                    continue
                seen.add(key)
                out.append({
                    "kind": "unsync-pair", "store": name,
                    "write_site": a["site"], "other_site": b["site"],
                    "other_op": b["op"],
                    "write_stack": a["stack"],
                    "other_stack": b["stack"]})
    return out


# -- session freeze/thaw ---------------------------------------------

_SNAPSHOT_CLASSES = None


def _classes():
    global _SNAPSHOT_CLASSES
    if _SNAPSHOT_CLASSES is None:
        from volcano_tpu.api.job_info import (JobInfo, SubJobInfo,
                                              TaskInfo)
        from volcano_tpu.api.node_info import NodeInfo
        from volcano_tpu.api.queue_info import QueueInfo
        from volcano_tpu.api.resource import Resource
        _SNAPSHOT_CLASSES = (NodeInfo, JobInfo, TaskInfo, SubJobInfo,
                             QueueInfo, Resource)
    return _SNAPSHOT_CLASSES


def _ensure_patched() -> None:
    """Install the class-level barriers + seam wrappers (idempotent;
    deferred to first freeze so every class is importable)."""
    global _PATCHED
    if _PATCHED:
        return
    _PATCHED = True
    from volcano_tpu.api.job_info import JobInfo
    from volcano_tpu.api.resource import Resource
    from volcano_tpu.framework.session import Session
    from volcano_tpu.framework.statement import Statement
    nodeinfo, jobinfo, taskinfo, subjobinfo, queueinfo, _ = _classes()
    for cls in (nodeinfo, jobinfo, taskinfo, subjobinfo, queueinfo):
        _guard_setattr(cls)
    # Resource accounting objects mutate in place through these; the
    # frozen registry holds the node's idle/used/releasing/pipelined
    for m in ("add", "sub", "sub_unchecked"):
        _guard_method(Resource, m)
    # the designated mutation seams
    for cls, methods in (
            (Session, ("allocate", "pipeline", "evict", "deallocate",
                       "unevict", "set_job_pending_reason")),
            (Statement, ("commit", "rollback_to")),
            (JobInfo, ("record_fit_error", "set_job_fit_errors"))):
        for m in methods:
            orig = cls.__dict__.get(m)
            if orig is None or getattr(orig, "_vtp_seam", False):
                continue
            is_commit = cls is Statement and m == "commit"

            def seamed(self, *a, _orig=orig,
                       _name=f"{cls.__name__}.{m}",
                       _commit=is_commit, **kw):
                if _commit:
                    # the session's first commit closes the strict
                    # single-threaded freeze window
                    note_commit(self.ssn.uid)
                with _Seam(_name):
                    return _orig(self, *a, **kw)

            seamed._vtp_seam = True
            seamed._vtp_orig = orig
            setattr(cls, m, seamed)


def maybe_freeze_session(ssn) -> None:
    """Deep-freeze *ssn*'s snapshot (called at the end of
    open_session when the audit is armed: plugins have finished their
    on_session_open setup, the sweep phase begins)."""
    if not _ACTIVE:
        return
    _ensure_patched()
    uid = ssn.uid
    objects: List[int] = []
    register = objects.append

    # the snapshot maps themselves become barrier dicts (mutating the
    # node/job/queue SET mid-session is never legal); swapping the
    # hot per-object dicts happens BEFORE the ids are published to
    # the frozen registry, so none of this setup trips its own
    # barriers
    ssn.nodes = FrozenDict(ssn.nodes, "session.nodes")
    ssn.jobs = FrozenDict(ssn.jobs, "session.jobs")
    ssn.queues = FrozenDict(ssn.queues, "session.queues")
    for node in ssn.nodes.values():
        node.tasks = FrozenDict(node.tasks,
                                f"node[{node.name}].tasks")
        node.occupied_ports = FrozenDict(
            node.occupied_ports, f"node[{node.name}].ports")
        register(id(node))
        for res in (node.idle, node.used, node.releasing,
                    node.pipelined, node.oversubscription):
            register(id(res))
        register(id(node.tasks))
        register(id(node.occupied_ports))
    for job in ssn.jobs.values():
        register(id(job))
        for task in job.tasks.values():
            register(id(task))
        for sub in job.sub_jobs.values():
            register(id(sub))
    for queue in ssn.queues.values():
        register(id(queue))
    for m in (ssn.nodes, ssn.jobs, ssn.queues):
        register(id(m))
    # arm the TSan-lite half on the session's owner-confined stores
    # (see track()): the dispatch memos are resolved on this thread
    # before any fan-out — a pool worker reading one is the leak the
    # unsync-pair detector exists to catch
    ssn._enabled_cache = track(ssn._enabled_cache,
                               "session._enabled_cache")
    ssn._raw_cache = track(ssn._raw_cache, "session._raw_cache")
    with _REG_LOCK:
        _SESSIONS[uid] = {"owner": threading.get_ident(),
                          "committed": False, "objects": objects}
        for oid in objects:
            _FROZEN[oid] = uid
        _COUNTS["sessions"] += 1
        _COUNTS["frozen_objects"] += len(objects)


def note_commit(ssn_uid: str) -> None:
    """First Statement commit: the strict single-threaded window
    closes (cross-thread and in-fanout writes stay violations)."""
    if not _ACTIVE:
        return
    sess = _SESSIONS.get(ssn_uid)
    if sess is not None:
        sess["committed"] = True


def thaw_session(ssn) -> None:
    """Lift the freeze at close_session: the job updater and the
    cache's post-session bookkeeping mutate freely again."""
    if not _ACTIVE:
        return
    sess = _SESSIONS.pop(ssn.uid, None)
    if sess is None:
        return
    with _REG_LOCK:
        for oid in sess["objects"]:
            _FROZEN.pop(oid, None)


# -- fan-out regions --------------------------------------------------

def fanout_begin() -> None:
    """A parallel sweep is taking flight: between begin and end the
    snapshot is read-only for EVERY thread and seams are barred."""
    if not _ACTIVE:
        return
    _FANOUT["depth"] += 1
    _FANOUT["owner"] = threading.get_ident()
    _COUNTS["fanout_regions"] += 1


def fanout_end() -> None:
    if not _ACTIVE:
        return
    _FANOUT["depth"] = max(0, _FANOUT["depth"] - 1)


def fanout_active() -> bool:
    return _FANOUT["depth"] > 0


# -- lifecycle --------------------------------------------------------

def install() -> None:
    global _ACTIVE
    _ACTIVE = True


def uninstall() -> None:
    """Disarm recording.  Class patches stay installed (they no-op
    when inactive) — un-patching live classes mid-process would race
    the very code paths this module audits."""
    global _ACTIVE
    _ACTIVE = False


def enabled() -> bool:
    return _ACTIVE


def reset() -> None:
    with _REG_LOCK:
        _FROZEN.clear()
        _SESSIONS.clear()
        _VIOLATIONS.clear()
        _SEEN.clear()
        _TRACKS.clear()
        _FANOUT["depth"] = 0
        _FANOUT["owner"] = None
        for k in _COUNTS:
            _COUNTS[k] = 0


def report() -> dict:
    with _REG_LOCK:
        violations = list(_VIOLATIONS)
        counts = dict(_COUNTS)
        tracked = {name: len(rows) for name, rows in _TRACKS.items()}
    violations += _unsync_pairs()
    return {
        "pid": os.getpid(),
        "sessions_frozen": counts["sessions"],
        "objects_frozen": counts["frozen_objects"],
        "fanout_regions": counts["fanout_regions"],
        "tracked_stores": tracked,
        "violations": violations,
    }


def flush(path: Optional[str] = None) -> Optional[str]:
    """Write the report atomically; returns the path written."""
    out_dir = path or os.environ.get(ENV_OUT, "")
    if not out_dir:
        return None
    import json
    os.makedirs(out_dir, exist_ok=True)
    fpath = os.path.join(out_dir, f"raceaudit-{os.getpid()}.json")
    tmp = fpath + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(report(), f, indent=1, default=str)
    os.replace(tmp, fpath)
    return fpath


def install_from_env() -> None:
    """Arm from VTP_RACE_AUDIT (called by volcano_tpu/__init__).  With
    VTP_RACE_AUDIT_OUT set, the report flushes at exit AND at 2Hz
    from a daemon thread, so a SIGKILL'd chaos incarnation still
    reports; SIGTERM flushes once then chains to the previous
    disposition (same contract as lockaudit.install_from_env)."""
    if not os.environ.get(ENV_FLAG):
        return
    install()
    if not os.environ.get(ENV_OUT):
        return
    import atexit
    atexit.register(flush)
    import signal
    prev = signal.getsignal(signal.SIGTERM)

    def _flush_on_term(signum, frame):
        try:
            flush()
        except OSError:
            # vtplint: disable=except-pass (mid-shutdown best effort; the 2Hz flusher already wrote a near-final report)
            pass
        if callable(prev) and prev not in (signal.SIG_DFL,
                                           signal.SIG_IGN):
            prev(signum, frame)
        else:
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

    try:
        signal.signal(signal.SIGTERM, _flush_on_term)
    except ValueError:
        # vtplint: disable=except-pass (not the main thread: signal registration is impossible, the 2Hz flusher remains the fallback)
        pass

    def _flusher():
        import time
        while True:
            time.sleep(0.5)
            try:
                flush()
            except OSError:
                # vtplint: disable=except-pass (2Hz best-effort report flusher; the atexit flush is the authoritative write)
                pass

    threading.Thread(target=_flusher, name="raceaudit-flush",
                     daemon=True).start()
