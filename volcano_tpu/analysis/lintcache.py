"""mtime-keyed lint result cache (``.vtplint_cache/``).

The rule set grows every PR (six AST rules at PR 10, eight plus a
whole-program ownership pass now) while the tier-1 lint gate's wall
time must not: vtplint re-lints only what changed.

Two granularities, one JSON file:

  per-file   the astlint AST rules and the flakes pass are pure
             functions of one file's bytes — results key on
             ``mtime_ns:size`` per file.
  per-tree   the racecheck ownership pass is whole-program (its call
             graph crosses files), so its result keys on a digest of
             EVERY in-domain file's signature: one byte changed
             anywhere re-runs the pass, nothing changed replays it.

The cache version is a digest of the analysis toolchain's own
sources (astlint/flakes/racecheck/registry/schema/lintcache +
tools/vtplint.py + bundle.py, whose FAMILIES tables feed the metric
rules): editing ANY rule invalidates every cached result — a stale
green from an older rule set is worse than a slow gate.  Registry
cross-checks run live every time (they verify the imported package,
not file bytes).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import List, Optional

from volcano_tpu.analysis.astlint import Finding

CACHE_DIR = ".vtplint_cache"

_TOOLCHAIN = (
    "volcano_tpu/analysis/astlint.py",
    "volcano_tpu/analysis/flakes.py",
    "volcano_tpu/analysis/racecheck.py",
    "volcano_tpu/analysis/registry.py",
    "volcano_tpu/analysis/schema.py",
    "volcano_tpu/analysis/lintcache.py",
    "volcano_tpu/bundle.py",
    "tools/vtplint.py",
)


def file_sig(path: str) -> Optional[str]:
    try:
        st = os.stat(path)
    except OSError:
        return None
    return f"{st.st_mtime_ns}:{st.st_size}"


def _encode(findings: List[Finding]) -> list:
    return [{"rule": f.rule, "path": f.path, "line": f.line,
             "msg": f.msg, "suppressed": f.suppressed}
            for f in findings]


def _decode(rows: list) -> List[Finding]:
    return [Finding(r["rule"], r["path"], r["line"], r["msg"],
                    r.get("suppressed")) for r in rows]


class LintCache:
    def __init__(self, root: str, cache_dir: str = CACHE_DIR):
        self.root = root
        self.path = os.path.join(root, cache_dir, "results.json")
        self.version = self._toolchain_sig()
        self.dirty = False
        self.data: dict = {"version": self.version, "files": {},
                           "trees": {}}
        try:
            with open(self.path, encoding="utf-8") as f:
                loaded = json.load(f)
            if loaded.get("version") == self.version:
                self.data = loaded
        except (OSError, ValueError):
            # vtplint: disable=except-pass (a missing or torn cache file IS the cold-cache outcome; the pass re-runs and rewrites it)
            pass

    def _toolchain_sig(self) -> str:
        h = hashlib.sha256()
        for rel in _TOOLCHAIN:
            h.update(rel.encode())
            h.update(str(file_sig(os.path.join(self.root, rel)))
                     .encode())
        return h.hexdigest()[:16]

    # -- per-file ------------------------------------------------------

    def get_file(self, pass_name: str,
                 path: str) -> Optional[List[Finding]]:
        entry = self.data["files"].get(f"{pass_name}:{path}")
        if entry is None or entry.get("sig") != file_sig(path):
            return None
        return _decode(entry["findings"])

    def put_file(self, pass_name: str, path: str,
                 findings: List[Finding]) -> None:
        self.data["files"][f"{pass_name}:{path}"] = {
            "sig": file_sig(path), "findings": _encode(findings)}
        self.dirty = True

    # -- per-tree ------------------------------------------------------

    def tree_sig(self, paths: List[str]) -> str:
        h = hashlib.sha256()
        for p in sorted(paths):
            h.update(p.encode())
            h.update(str(file_sig(p)).encode())
        return h.hexdigest()[:16]

    def get_tree(self, pass_name: str,
                 sig: str) -> Optional[List[Finding]]:
        entry = self.data["trees"].get(pass_name)
        if entry is None or entry.get("sig") != sig:
            return None
        return _decode(entry["findings"])

    def put_tree(self, pass_name: str, sig: str,
                 findings: List[Finding]) -> None:
        self.data["trees"][pass_name] = {
            "sig": sig, "findings": _encode(findings)}
        self.dirty = True

    # -- persistence ---------------------------------------------------

    def save(self) -> None:
        if not self.dirty:
            return
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.data, f)
        os.replace(tmp, self.path)
        self.dirty = False
