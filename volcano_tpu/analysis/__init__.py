"""Project-native static analysis + runtime concurrency auditing.

The concurrency and wire invariants this control plane bled for —
fsync-before-ack, merge-under-one-lock-hold, monotonic-only leases,
bounded metric labels, idempotency keys on mutating POSTs — used to
live in CHANGES.md prose and scattered per-PR tests.  Before the
scheduler cycle goes parallel (ROADMAP item 3), they are enforced by
tooling that fails tier-1, not by reviewer memory:

  astlint.py    AST rules over the whole package (req-id, wall-clock,
                metric-family, metric-labels, append-lock, except-pass)
                with inline ``# vtplint: disable=<rule> (<reason>)``
                suppressions — a suppression without a reason is
                itself a finding.
  flakes.py     a pyflakes-shaped pass (syntax, unused imports) that
                uses the real pyflakes when installed and a built-in
                conservative fallback when not (this image bakes no
                linters in).
  registry.py   runtime registry cross-checks: every codec wire class
                round-trips, every store kind exists, every generated
                metric family is declared.
  schema.py     the metric label schema checker over a live
                Prometheus exposition (bundle.FAMILY_LABELS is the
                declaration; this is the enforcement) — subsumes the
                per-PR label-cardinality tests.
  lockaudit.py  opt-in runtime lock-order auditor in the faults.py
                mold: wraps threading.Lock/RLock/Condition creation,
                records the acquisition graph, fails on inversions/
                cycles/guarded-store mutation without the owning lock.

``tools/vtplint.py`` is the CLI over all of it; ``tests/test_lint.py``
wires it into tier-1.  Keep this module import-light: lockaudit is
imported from ``volcano_tpu/__init__`` when VTP_LOCK_AUDIT is set,
before any lock exists.
"""
