"""Runtime metric label-schema enforcement over a live exposition.

``bundle.FAMILY_LABELS`` declares, per family, which label keys may
appear and what values they may carry (closed enum / operator config /
per-object key with a deletion lifecycle).  ``check_exposition`` runs
the declaration against a real Prometheus text dump — the dynamic
half of the bounded-cardinality contract, covering the label values
no static pass can see (f-string families, computed label values).

This subsumes the three per-PR cardinality tests (trace / elastic /
goodput) that each re-implemented a slice of it by hand:
tests/test_lint.py drives a real scheduling session and feeds the
whole exposition through here instead.
"""

from __future__ import annotations

import re
from typing import Dict, List, Tuple

_LINE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)$")
_LABEL_RE = re.compile(r'(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>(?:'
                       r'[^"\\]|\\.)*)"')


def parse_exposition(text: str) -> List[Tuple[str, Dict[str, str]]]:
    """(family, labels) per sample line; _count/_sum histogram
    suffixes fold back onto their family name."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _LINE_RE.match(line)
        if m is None:
            out.append(("<unparseable>", {"line": line}))
            continue
        name = m.group("name")
        labels = {lm.group("k"): lm.group("v")
                  for lm in _LABEL_RE.finditer(m.group("labels") or "")}
        out.append((name, labels))
    return out


def check_exposition(text: str, families=None,
                     family_labels=None) -> List[str]:
    """Violation strings for every sample breaking the declared
    schema (empty list == the exposition honours the contract)."""
    if families is None or family_labels is None:
        from volcano_tpu.bundle import FAMILIES, FAMILY_LABELS
        families = FAMILIES if families is None else families
        family_labels = FAMILY_LABELS if family_labels is None \
            else family_labels
    from volcano_tpu.analysis.astlint import _Enums
    enums = _Enums()
    violations: List[str] = []
    for name, labels in parse_exposition(text):
        if name == "<unparseable>":
            violations.append(f"unparseable exposition line: "
                              f"{labels['line']!r}")
            continue
        fam = name
        if fam not in families:
            base = re.sub(r"_(count|sum)$", "", fam)
            if base in families:
                fam = base
            else:
                violations.append(
                    f"{name}: family not declared in bundle.FAMILIES")
                continue
        declared = family_labels.get(fam, {})
        for key, val in labels.items():
            spec = declared.get(key)
            if spec is None:
                violations.append(
                    f"{name}: label {key}={val!r} not declared for "
                    f"this family (undeclared keys are how job-key "
                    f"cardinality leaks in)")
                continue
            allowed = enums.resolve(spec)
            if allowed is not None and val not in allowed:
                violations.append(
                    f"{name}: label {key}={val!r} outside its "
                    f"bounded enum {sorted(allowed)}")
    return violations
