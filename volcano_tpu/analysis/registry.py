"""Runtime registry cross-checks: the contracts no AST pass can see.

These import the live package and verify the three registries agree
with each other and with the code that feeds them:

  wire-roundtrip   every class the codec registers encodes and
                   decodes back to an equal encoding (a wire type
                   whose fields the codec cannot carry would corrupt
                   the first snapshot that ships one).  Instances are
                   synthesized from dataclass defaults, with simple
                   placeholder values for required fields.
  kind-registry    every kind in cache.kinds.KINDS maps to a real
                   store attribute on FakeCluster, and typed kinds
                   can derive a key.
  family-coverage  every family the code can generate is declared in
                   bundle.FAMILIES and every FAMILY_LABELS row points
                   at a declared family; every 'enum:' label spec
                   resolves.  This is what caught the eleven live
                   queue_* families and two whole subsystems
                   (audit exporter, mirror resync) the table had
                   silently drifted from.
"""

from __future__ import annotations

import dataclasses
import enum
import typing
from typing import List

from volcano_tpu.analysis.astlint import Finding

_PLACEHOLDERS = {str: "x", int: 1, float: 1.0, bool: True,
                 dict: {}, list: [], tuple: (), set: set(),
                 frozenset: frozenset()}


def _synthesize(cls):
    """Best-effort instance of a registered wire dataclass: defaults
    where declared, simple placeholders for required simple fields."""
    kwargs = {}
    hints = typing.get_type_hints(cls)
    for f in dataclasses.fields(cls):
        if f.default is not dataclasses.MISSING or \
                f.default_factory is not dataclasses.MISSING:
            continue
        hint = hints.get(f.name, str)
        origin = typing.get_origin(hint) or hint
        if origin in _PLACEHOLDERS:
            kwargs[f.name] = _PLACEHOLDERS[origin]
        elif isinstance(origin, type) and \
                issubclass(origin, enum.Enum):
            kwargs[f.name] = next(iter(origin))
        elif isinstance(origin, type) and dataclasses.is_dataclass(
                origin):
            kwargs[f.name] = _synthesize(origin)
        else:
            kwargs[f.name] = None
    return cls(**kwargs)


def check_wire_roundtrip() -> List[Finding]:
    from volcano_tpu.api import codec
    codec._build_registry()
    findings: List[Finding] = []
    for name, cls in sorted(codec._CLASSES.items()):
        try:
            obj = _synthesize(cls)
            wire = codec.dumps(obj)
            back = codec.loads(wire)
            if codec.dumps(back) != wire:
                findings.append(Finding(
                    "wire-roundtrip", "volcano_tpu/api/codec.py", 0,
                    f"{name}: decode(encode(x)) re-encodes "
                    f"differently — a lossy wire type"))
        except Exception as e:  # noqa: BLE001 — each failure reported
            findings.append(Finding(
                "wire-roundtrip", "volcano_tpu/api/codec.py", 0,
                f"{name}: does not round-trip through the codec "
                f"({type(e).__name__}: {e})"))
    return findings


def check_kind_registry() -> List[Finding]:
    from volcano_tpu.cache.fake_cluster import FakeCluster
    from volcano_tpu.cache.kinds import KINDS
    findings: List[Finding] = []
    cluster = FakeCluster()
    for kind, spec in sorted(KINDS.items()):
        store = getattr(cluster, spec.attr, None)
        if store is None:
            findings.append(Finding(
                "kind-registry", "volcano_tpu/cache/kinds.py", 0,
                f"kind {kind!r} names store attribute "
                f"{spec.attr!r} which FakeCluster does not have"))
        elif not hasattr(store, "items"):
            findings.append(Finding(
                "kind-registry", "volcano_tpu/cache/kinds.py", 0,
                f"kind {kind!r} store {spec.attr!r} is not a "
                f"mapping (snapshot encoding iterates .items())"))
    return findings


def check_family_coverage() -> List[Finding]:
    from volcano_tpu import goodput
    from volcano_tpu.analysis.astlint import _Enums
    from volcano_tpu.bundle import (FAMILIES, FAMILY_LABELS,
                                    agent_dashboard,
                                    dashboard_metric_names,
                                    scheduler_dashboard)
    findings: List[Finding] = []
    for fam in FAMILY_LABELS:
        if fam not in FAMILIES:
            findings.append(Finding(
                "family-coverage", "volcano_tpu/bundle.py", 0,
                f"FAMILY_LABELS declares {fam!r} which is not in "
                f"FAMILIES"))
    enums = _Enums()
    for fam, labels in FAMILY_LABELS.items():
        for key, spec in labels.items():
            try:
                enums.resolve(spec)
            except Exception as e:  # noqa: BLE001 — reported per spec
                findings.append(Finding(
                    "family-coverage", "volcano_tpu/bundle.py", 0,
                    f"label spec {fam}.{key} = {spec!r} does not "
                    f"resolve ({e})"))
    for fam in goodput.SESSION_GAUGE_FAMILIES:
        if fam not in FAMILIES:
            findings.append(Finding(
                "family-coverage", "volcano_tpu/goodput.py", 0,
                f"SESSION_GAUGE_FAMILIES exports {fam!r} which is "
                f"not declared in FAMILIES"))
    for dash in (scheduler_dashboard(), agent_dashboard()):
        for fam in dashboard_metric_names(dash):
            if fam not in FAMILIES:
                findings.append(Finding(
                    "family-coverage", "volcano_tpu/bundle.py", 0,
                    f"dashboard {dash['uid']} queries undeclared "
                    f"family {fam!r}"))
    return findings


def check_all() -> List[Finding]:
    return (check_wire_roundtrip() + check_kind_registry()
            + check_family_coverage())
