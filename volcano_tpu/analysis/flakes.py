"""A pyflakes-shaped pass with a zero-dependency fallback.

Tier-1 wants a basic hygiene gate (syntax errors, unused imports)
alongside the project rules.  The container image bakes in no linter,
so: when the real ``pyflakes`` is importable it runs (full checker);
otherwise a conservative built-in fallback covers the two highest-
signal checks without its false-positive surface:

  syntax-error    the file does not parse
  unused-import   an imported binding never referenced by any Name in
                  the module (attribute roots included).  Skipped for
                  __init__.py (re-export surface), ``from __future__``,
                  imports inside try/except (optional-dependency
                  gating), names in ``__all__``, underscore bindings,
                  and lines carrying ``noqa``.

Conservative by design: a missed unused import is cheap, a false
positive that fails tier-1 is not.
"""

from __future__ import annotations

import ast
from typing import List, Set

from volcano_tpu.analysis.astlint import Finding


def _real_pyflakes(src: str, path: str):
    try:
        from pyflakes.api import check
        from pyflakes.reporter import Reporter
    except ImportError:
        return None
    import io

    class _Cap(io.StringIO):
        pass

    out, err = _Cap(), _Cap()
    check(src, path, Reporter(out, err))
    findings = []
    for line in out.getvalue().splitlines():
        # "<path>:<line>:<col>: <msg>" (pyflakes >= 3) or without col
        parts = line.split(":", 3)
        if len(parts) >= 3 and parts[1].strip().isdigit():
            lineno = int(parts[1])
            msg = parts[-1].strip()
            findings.append(Finding("pyflakes", path, lineno, msg))
    return findings


def check_source(src: str, path: str) -> List[Finding]:
    real = _real_pyflakes(src, path)
    if real is not None:
        return real
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding("syntax-error", path, e.lineno or 0,
                        f"cannot parse: {e.msg}")]
    if path.endswith("__init__.py"):
        return []
    lines = src.splitlines()

    used: Set[str] = set()
    exported: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__" and \
                        isinstance(node.value, (ast.List, ast.Tuple)):
                    exported.update(
                        e.value for e in node.value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str))

    in_try: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Try):
            for sub in ast.walk(node):
                in_try.add(id(sub))
        elif isinstance(node, ast.If):
            # `if TYPE_CHECKING:` imports feed quoted annotations the
            # AST cannot see — never report them
            test = node.test
            name = test.attr if isinstance(test, ast.Attribute) \
                else getattr(test, "id", "")
            if name == "TYPE_CHECKING":
                for sub in ast.walk(node):
                    in_try.add(id(sub))

    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Import, ast.ImportFrom)):
            continue
        if isinstance(node, ast.ImportFrom) and \
                node.module == "__future__":
            continue
        if id(node) in in_try:
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) \
            else ""
        if "noqa" in line:
            continue
        for alias in node.names:
            if alias.name == "*":
                continue
            bound = alias.asname or alias.name.split(".")[0]
            if bound.startswith("_") or bound in exported:
                continue
            if bound not in used:
                findings.append(Finding(
                    "unused-import", path, node.lineno,
                    f"{bound!r} imported but unused"))
    return findings


def check_paths(paths) -> List[Finding]:
    import os
    findings: List[Finding] = []
    for path in paths:
        if os.path.isfile(path):
            with open(path, encoding="utf-8") as f:
                findings.extend(check_source(f.read(), path))
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            for fname in sorted(files):
                if not fname.endswith(".py"):
                    continue
                fpath = os.path.join(root, fname)
                with open(fpath, encoding="utf-8") as f:
                    findings.extend(check_source(f.read(), fpath))
    return findings
