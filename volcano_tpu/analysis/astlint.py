"""AST rules for the project's own invariants (the vtplint core).

Each rule exists because a real incident or review burned us (the
catalog with provenance lives in docs/design/static-analysis.md):

  req-id         a mutating wire POST through the client's _request
                 seam must carry an idempotency key
                 (idempotency_key=True) or be explicitly suppressed as
                 replay-safe-by-state-compare — the PR 4/8 double-
                 apply class.
  wall-clock     time.time() is banned in the lease/WAL/election
                 paths (server/durability.py, server/state_server.py,
                 server/replication.py, and any function named like a
                 lease/election/wal path elsewhere): deadlines are
                 monotonic-only; wall time appears only where the
                 wire/disk format needs it, suppressed with the
                 rebase story.
  metric-family  every literal metric name at an emission site must
                 be declared in bundle.FAMILIES (dashboards and the
                 scrape contract are generated from that table).
  metric-labels  label keyword keys must be declared for the family
                 in bundle.FAMILY_LABELS, and literal label values of
                 enum-typed labels must be members — the bounded-
                 cardinality contract, statically.
  append-lock    a durable append (self.durable.append*/append_event)
                 in server code must happen inside a lock-holding
                 ``with`` block, so WAL order cannot drift from the
                 order the lock assigned (rv order == journal order
                 is what makes replay exact).  Order-independent
                 records suppress with the reason.
  except-pass    a broad exception handler that silently swallows
                 (pass/continue-only body) around wire/disk I/O —
                 gray failures must be counted or classified, never
                 eaten.
  process-ship-purity
                 in any module touching multiprocessing, a pipe
                 ``.send(...)``/``.send_bytes(...)`` may only happen
                 inside the designated ship seam
                 (actions/procpool.post/post_bytes), whose pickler
                 REFUSES callables — the pickled-callback purity
                 contract of the process-pool sweep: worker behavior
                 comes from worker-side resolution, never from
                 shipped code.
  episode-propagation
                 a function POSTing a mutating federation RPC
                 (add_vcjob / delete_vcjob / update_podgroup_status /
                 reap_residuals through FedRPC.call) or opening a
                 controller episode (FailoverEpisode / ResizeEpisode)
                 must thread the causal episode ID — reference the
                 episode API (episode_of/ensure_episode/FED_EPISODE*)
                 or pass episode= — or carry a reasoned waiver.  A
                 cross-plane hop that drops the ID is invisible to
                 `GET /fleet_trace?episode=`: the stitched tree holes
                 exactly where the bug is.
  fed-retry      in volcano_tpu/federation/ (except retry.py, which
                 IS the policy), a retry loop may not sleep a fixed
                 literal delay: every cross-region wait goes through
                 federation.retry.backoff_delay (capped exponential,
                 deterministic jitter — seeded chaos replays exactly)
                 or the FedRPC breaker.  A fleet of routers/mirrors
                 hot-looping a constant delay against a struggling
                 region is a synchronized retry stampede.

Suppressions: ``# vtplint: disable=<rule>[,<rule>] (<reason>)`` on the
finding's line or the line above.  A suppression WITHOUT a
parenthesized reason is reported as ``unexplained-suppression`` and
fails --strict: the inventory of explained suppressions is part of
the shipped artifact, a reason-free one is just a muted bug.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

RULES = ("req-id", "wall-clock", "metric-family", "metric-labels",
         "append-lock", "except-pass", "process-ship-purity",
         "fed-retry", "episode-propagation")

SUPPRESS_RE = re.compile(
    r"#\s*vtplint:\s*disable=([a-z0-9*,_-]+)(?:\s*\(([^)]+)\))?")

# wall-clock rule scope: the monotonic-only files...
WALL_CLOCK_FILES = ("server/durability.py", "server/state_server.py",
                    "server/replication.py")
# ...and, anywhere else, functions that ARE a lease/election/WAL path
WALL_CLOCK_FN = re.compile(r"lease|election|campaign|promote|_wal",
                           re.IGNORECASE)

# append-lock rule scope (the callers of the durability seam; the
# DurableStore implementation takes its own internal lock)
APPEND_LOCK_FILES = ("server/state_server.py", "server/replication.py")
APPEND_METHODS = frozenset({"append", "append_event", "append_shipped"})

# process-ship-purity: the only functions allowed to call a pipe send
# (both live in actions/procpool.py and route through the pure
# pickler that refuses callables)
SHIP_SEAMS = frozenset({"post", "post_bytes"})
SHIP_SENDS = frozenset({"send", "send_bytes"})

# fed-retry rule scope: the federation tier, minus the shared policy
# module itself (its constants ARE the delays)
FED_RETRY_DIR = "volcano_tpu/federation/"
FED_RETRY_EXEMPT = ("federation/retry.py",)
SLEEP_METHODS = frozenset({"sleep", "wait"})

# episode-propagation scope: the mutating federation RPC verbs that
# move a gang between planes (advance_fence is term plumbing, not a
# causal hop), the controllers' episode state machines, and the names
# whose presence in the enclosing function counts as threading the ID
FED_MUTATING_OPS = frozenset({"add_vcjob", "delete_vcjob",
                              "update_podgroup_status",
                              "reap_residuals"})
EPISODE_CTORS = frozenset({"FailoverEpisode", "ResizeEpisode"})
EPISODE_CTOR_DIR = "volcano_tpu/controllers/"
EPISODE_API = frozenset({"episode", "episode_id", "episode_of",
                         "episode_hop", "episode_ts",
                         "ensure_episode"})

EMIT_METHODS = frozenset({"inc", "observe", "set_gauge"})
READ_METHODS = frozenset({"get_gauge", "get_counter",
                          "get_observations", "quantile",
                          "clear_gauge_series"})

BROAD_EXCEPTS = frozenset({"Exception", "BaseException", "OSError",
                           "IOError"})
IO_HINTS = frozenset({
    "open", "open_append", "urlopen", "fsync", "unlink", "rename",
    "remove", "makedirs", "rmtree", "replace", "truncate", "getsize",
    "sendall", "recv", "connect", "setsockopt", "shutdown",
    "_request", "_request_once", "http_json", "read", "write",
    "readlines", "flush",
})


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    msg: str
    suppressed: Optional[str] = None    # the reason text when waived

    def format(self) -> str:
        tag = f" [suppressed: {self.suppressed}]" if self.suppressed \
            else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.msg}{tag}"


def _suppressions(src: str) -> Dict[int, Tuple[Set[str], str]]:
    """line -> (rules, reason).  reason '' == unexplained."""
    out: Dict[int, Tuple[Set[str], str]] = {}
    for i, line in enumerate(src.splitlines(), start=1):
        m = SUPPRESS_RE.search(line)
        if m:
            rules = {r.strip() for r in m.group(1).split(",") if r}
            out[i] = (rules, (m.group(2) or "").strip())
    return out


def _attr_chain(node: ast.AST) -> str:
    """Dotted-source-ish rendering of an attribute chain for matching
    ("self.durable.append" -> "self.durable.append")."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _literal_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class _Enums:
    """Lazy resolver for 'enum:<module>:<NAME>' label specs."""

    def __init__(self) -> None:
        self._cache: Dict[str, tuple] = {}

    def resolve(self, spec) -> Optional[tuple]:
        if isinstance(spec, (tuple, list, set, frozenset)):
            return tuple(spec)
        if isinstance(spec, str) and spec.startswith("enum:"):
            if spec not in self._cache:
                import importlib
                _, mod, name = spec.split(":", 2)
                self._cache[spec] = tuple(
                    getattr(importlib.import_module(mod), name))
            return self._cache[spec]
        return None        # CONFIG / OBJECT: not statically checkable


def match_waivers(findings, src: str, path: str) -> List[Finding]:
    """Match raw findings against *src*'s inline suppressions (shared
    by the AST rules and the racecheck ownership pass).

    The waiver may sit on the finding's line or the line above;
    except-pass alone also honours the first handler-body line (the
    comment rides next to the ``pass`` it explains).  The window stays
    this tight on purpose: a wider one would let a NEW violation
    written adjacent to an existing waiver inherit that waiver's
    reason.  Every candidate is checked for the rule (a neighboring
    waiver for a different rule never shadows a match)."""
    sup = _suppressions(src)
    out: List[Finding] = []
    for f in findings:
        lines = [f.line, f.line - 1]
        if f.rule == "except-pass":
            lines.append(f.line + 1)
        waiver = next(
            (w for w in (sup.get(ln) for ln in lines)
             if w and (f.rule in w[0] or "*" in w[0])), None)
        if waiver:
            f.suppressed = waiver[1] or None
            if not waiver[1]:
                out.append(Finding(
                    "unexplained-suppression", path, f.line,
                    f"suppression of [{f.rule}] carries no "
                    f"(reason) — every waiver must say why"))
        out.append(f)
    return out


class Linter:
    """One AST pass over one file; yields Findings (already matched
    against the file's inline suppressions)."""

    def __init__(self, families: Optional[dict] = None,
                 family_labels: Optional[dict] = None):
        if families is None or family_labels is None:
            from volcano_tpu.bundle import FAMILIES, FAMILY_LABELS
            families = FAMILIES if families is None else families
            family_labels = FAMILY_LABELS if family_labels is None \
                else family_labels
        self.families = families
        self.family_labels = family_labels
        self._enums = _Enums()

    # -- entry ----------------------------------------------------------

    def lint_source(self, src: str, path: str) -> List[Finding]:
        rel = path.replace("\\", "/")
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            return [Finding("syntax-error", path, e.lineno or 0,
                            f"cannot parse: {e.msg}")]
        return match_waivers(self._walk(tree, rel), src, path)

    def lint_file(self, path: str) -> List[Finding]:
        with open(path, encoding="utf-8") as f:
            return self.lint_source(f.read(), path)

    # -- the pass -------------------------------------------------------

    def _walk(self, tree: ast.AST, rel: str) -> Iterator[Finding]:
        in_scope_file = rel.endswith(WALL_CLOCK_FILES)
        append_scope = rel.endswith(APPEND_LOCK_FILES)
        is_metrics_impl = rel.endswith("volcano_tpu/metrics.py")
        fed_scope = FED_RETRY_DIR in rel and \
            not rel.endswith(FED_RETRY_EXEMPT)
        fed_flagged: Set[int] = set()
        ctor_scope = EPISODE_CTOR_DIR in rel
        ship_scope = rel.endswith("actions/procpool.py") or any(
            (isinstance(n, ast.Import)
             and any(a.name.split(".")[0] == "multiprocessing"
                     for a in n.names))
            or (isinstance(n, ast.ImportFrom) and n.module
                and n.module.split(".")[0] == "multiprocessing")
            for n in ast.walk(tree))
        # ancestor context maintained by an explicit stack walk
        fn_stack: List[str] = []
        fn_nodes: List[ast.AST] = []
        lock_depth = [0]        # with-a-lock nesting count
        threads_cache: Dict[int, bool] = {}

        def threads_episode() -> bool:
            """Does the INNERMOST enclosing function reference the
            episode API anywhere in its body?  (Module-level code is
            never a hop — only reconcile/controller functions move
            gangs.)"""
            if not fn_nodes:
                return True
            fn = fn_nodes[-1]
            key = id(fn)
            if key not in threads_cache:
                threads_cache[key] = _references_episode(fn)
            return threads_cache[key]

        def locky(withitem: ast.withitem) -> bool:
            try:
                src = ast.unparse(withitem.context_expr)
            except Exception:  # noqa: BLE001 — unparse is best-effort
                return False
            return bool(re.search(r"lock|_cv|mutex", src, re.I))

        def visit(node: ast.AST) -> Iterator[Finding]:
            pushed_fn = False
            pushed_lock = False
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                fn_stack.append(node.name)
                fn_nodes.append(node)
                pushed_fn = True
            if isinstance(node, ast.With) and \
                    any(locky(i) for i in node.items):
                lock_depth[0] += 1
                pushed_lock = True
            if isinstance(node, ast.Call):
                yield from check_call(node)
            if isinstance(node, ast.Try):
                yield from check_try(node)
            if isinstance(node, (ast.While, ast.For)):
                yield from check_retry_loop(node)
            for child in ast.iter_child_nodes(node):
                yield from visit(child)
            if pushed_fn:
                fn_stack.pop()
                fn_nodes.pop()
            if pushed_lock:
                lock_depth[0] -= 1

        def check_call(node: ast.Call) -> Iterator[Finding]:
            chain = _attr_chain(node.func)
            attr = chain.rsplit(".", 1)[-1]

            # req-id --------------------------------------------------
            if attr == "_request" and node.args:
                method = _literal_str(node.args[0])
                if method == "POST":
                    keyed = any(
                        kw.arg == "idempotency_key" for kw in
                        node.keywords)
                    if not keyed:
                        route = _literal_str(node.args[1]) \
                            if len(node.args) > 1 else "?"
                        yield Finding(
                            "req-id", rel, node.lineno,
                            f"mutating POST {route or '<dynamic>'} "
                            f"without idempotency_key=True (_req_id): "
                            f"a retried ack-lost mutation may "
                            f"double-apply")

            # wall-clock ----------------------------------------------
            if chain == "time.time":
                in_scope = in_scope_file or any(
                    WALL_CLOCK_FN.search(fn) for fn in fn_stack)
                if in_scope:
                    yield Finding(
                        "wall-clock", rel, node.lineno,
                        "time.time() in a lease/WAL/election path — "
                        "deadlines are monotonic-only (a wall jump "
                        "mass-expires or immortalizes leases)")

            # append-lock ---------------------------------------------
            if append_scope and attr in APPEND_METHODS and \
                    "durable" in chain.split("."):
                if lock_depth[0] == 0:
                    yield Finding(
                        "append-lock", rel, node.lineno,
                        f"{chain}(...) outside a lock-holding `with` "
                        f"block: journal order may drift from the "
                        f"order the lock assigned")

            # process-ship-purity -------------------------------------
            if ship_scope and attr in SHIP_SENDS and \
                    isinstance(node.func, ast.Attribute):
                if not (fn_stack and fn_stack[-1] in SHIP_SEAMS):
                    yield Finding(
                        "process-ship-purity", rel, node.lineno,
                        f"{chain}(...) outside the ship seam "
                        f"(procpool.post/post_bytes): every cross-"
                        f"process payload must go through the pure "
                        f"pickler that refuses callables")

            # episode-propagation -------------------------------------
            if fed_scope and attr == "call" and \
                    "rpc" in chain.split(".") and len(node.args) >= 2:
                op = _literal_str(node.args[1])
                if op in FED_MUTATING_OPS and not threads_episode():
                    yield Finding(
                        "episode-propagation", rel, node.lineno,
                        f"mutating federation RPC {op!r} without "
                        f"threading the causal episode ID "
                        f"(episode_of/ensure_episode/FED_EPISODE*) — "
                        f"this hop would be invisible to "
                        f"GET /fleet_trace?episode=")
            if ctor_scope and \
                    chain.rsplit(".", 1)[-1] in EPISODE_CTORS and \
                    not threads_episode():
                yield Finding(
                    "episode-propagation", rel, node.lineno,
                    f"{chain.rsplit('.', 1)[-1]} opened without "
                    f"threading the causal episode ID — the "
                    f"controller's drain/recovery fragment would "
                    f"detach from the fleet trace")

            # metric-family / metric-labels ---------------------------
            if not is_metrics_impl and chain.startswith("metrics."):
                yield from check_metric(node, attr)

        def check_metric(node: ast.Call,
                         attr: str) -> Iterator[Finding]:
            names: List[str] = []
            if attr in EMIT_METHODS or attr in READ_METHODS:
                fam = _literal_str(node.args[0]) if node.args else None
                if fam is not None:
                    names = [fam]
            elif attr == "swap_gauge_families":
                if node.args and isinstance(
                        node.args[0], (ast.Tuple, ast.List, ast.Set)):
                    names = [n for n in map(_literal_str,
                                            node.args[0].elts)
                             if n is not None]
            elif attr == "resource_gauge_rows":
                prefix = _literal_str(node.args[0]) if node.args \
                    else None
                if prefix is not None:
                    names = [f"{prefix}_milli_cpu",
                             f"{prefix}_memory_bytes",
                             f"{prefix}_scalar_resources"]
            else:
                return
            for fam in names:
                if fam not in self.families:
                    yield Finding(
                        "metric-family", rel, node.lineno,
                        f"metric family {fam!r} is not declared in "
                        f"bundle.FAMILIES — dashboards and the scrape "
                        f"contract are generated from that table")
            if attr not in EMIT_METHODS or not names:
                return
            fam = names[0]
            declared = self.family_labels.get(fam, {})
            for kw in node.keywords:
                if kw.arg in (None, "value"):
                    continue
                if kw.arg not in declared:
                    yield Finding(
                        "metric-labels", rel, node.lineno,
                        f"label {kw.arg!r} is not declared for family "
                        f"{fam!r} in bundle.FAMILY_LABELS")
                    continue
                allowed = self._enums.resolve(declared[kw.arg])
                val = _literal_str(kw.value)
                if allowed is not None and val is not None and \
                        val not in allowed:
                    yield Finding(
                        "metric-labels", rel, node.lineno,
                        f"label {kw.arg}={val!r} is outside the "
                        f"bounded enum for family {fam!r}")

        def check_retry_loop(node: ast.AST) -> Iterator[Finding]:
            # fed-retry: a loop that both handles exceptions AND
            # sleeps a fixed literal delay is a bare retry loop —
            # the wait must come from the shared backoff policy
            if not fed_scope:
                return
            if not any(isinstance(n, ast.Try) for n in ast.walk(node)):
                return
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call) or not sub.args:
                    continue
                attr = _attr_chain(sub.func).rsplit(".", 1)[-1]
                if attr not in SLEEP_METHODS:
                    continue
                arg = sub.args[0]
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, (int, float)) and \
                        sub.lineno not in fed_flagged:
                    fed_flagged.add(sub.lineno)
                    yield Finding(
                        "fed-retry", rel, sub.lineno,
                        f"bare retry loop: fixed {arg.value}s delay "
                        f"in a federation retry path — use "
                        f"federation.retry.backoff_delay (capped "
                        f"exponential, deterministic jitter) or "
                        f"route the call through FedRPC, so a fleet "
                        f"of retriers never stampedes in lockstep")

        def check_try(node: ast.Try) -> Iterator[Finding]:
            if not _try_does_io(node):
                return
            for h in node.handlers:
                if _broad(h.type) and _silent(h.body):
                    what = ast.unparse(h.type) if h.type is not None \
                        else "bare except"
                    yield Finding(
                        "except-pass", rel, h.lineno,
                        f"{what} silently swallowed around wire/disk "
                        f"I/O — classify, count, or log it")

        return visit(tree)


def _references_episode(fn: ast.AST) -> bool:
    """Any mention of the episode API in *fn* counts as threading the
    ID: a read (episode_of), a mint (ensure_episode), the annotation
    constants (FED_EPISODE*), an `episode=` keyword, or a plain
    `episode` name/attribute the surrounding code assigned."""
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Name) and (
                sub.id in EPISODE_API or "FED_EPISODE" in sub.id):
            return True
        if isinstance(sub, ast.Attribute) and (
                sub.attr in EPISODE_API or "FED_EPISODE" in sub.attr):
            return True
        if isinstance(sub, ast.keyword) and sub.arg == "episode":
            return True
        if isinstance(sub, ast.arg) and sub.arg == "episode":
            return True
    return False


def _broad(t: Optional[ast.expr]) -> bool:
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in BROAD_EXCEPTS
    if isinstance(t, ast.Tuple):
        return any(_broad(e) for e in t.elts)
    return False


def _silent(body: List[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and \
                isinstance(stmt.value, ast.Constant):
            continue        # docstring / ellipsis
        return False
    return True


def _try_does_io(node: ast.Try) -> bool:
    for stmt in node.body:
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Call):
                name = _attr_chain(sub.func).rsplit(".", 1)[-1]
                if name in IO_HINTS:
                    return True
    return False


def lint_paths(paths, families=None,
               family_labels=None) -> List[Finding]:
    """Lint every .py under the given files/directories."""
    import os
    linter = Linter(families, family_labels)
    findings: List[Finding] = []
    for path in paths:
        if os.path.isfile(path):
            findings.extend(linter.lint_file(path))
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            for fname in sorted(files):
                if fname.endswith(".py"):
                    findings.extend(
                        linter.lint_file(os.path.join(root, fname)))
    return findings
