"""Opt-in runtime lock-order auditor (the `faults.py` of deadlocks).

Before the scheduler cycle fans out across threads and processes
(ROADMAP item 3), the ~22 ``threading.Lock/RLock/Condition`` sites in
server/cache/scheduler need their acquisition ORDER mechanically
checked, not remembered.  When armed (``VTP_LOCK_AUDIT=1`` in the
environment, or ``install()`` from a test), lock construction inside
this repository is wrapped so that every acquisition records:

  * the held-set of the acquiring thread -> directed edges between
    lock SITES (locks are named by their creation site, so every
    ``FakeCluster._lock`` instance aggregates onto one node);
  * an ``inversion`` violation the moment two sites are observed in
    both orders (the two stacks are kept — that pair IS a potential
    deadlock under the right interleaving);
  * a ``self-deadlock`` violation when a non-reentrant Lock is
    re-acquired (blocking) by its owner;
  * ``unguarded-mutation`` violations from guarded shared stores
    (``guard_store``): a mutation observed while the owning lock is
    not held.  ``metrics`` registries and the state server's
    lease/req-cache/chip-guard maps opt in when the audit is armed.

``report()`` summarizes the graph (+ cycles of any length via DFS)
and the violations; under the chaos conductor every process flushes
its report to ``VTP_LOCK_AUDIT_OUT`` so ``--lock-audit`` runs can
assert an empty violation set across the whole process plane.

Same-site edges (two INSTANCES from one creation site acquired
nested, e.g. operations spanning the server's store and a mirror) are
reported informationally, not as violations: site-level aggregation
cannot distinguish a benign fixed instance order from a true peer
cycle, and this auditor's findings must be actionable, never noisy.

The audit only wraps locks created while armed from files inside this
repository — stdlib internals (logging, queues, Events created by
``threading`` itself) keep raw primitives.
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Dict, List, Optional, Tuple

ENV_FLAG = "VTP_LOCK_AUDIT"
ENV_OUT = "VTP_LOCK_AUDIT_OUT"

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_REAL = {"Lock": threading.Lock, "RLock": threading.RLock,
         "Condition": threading.Condition}
_ACTIVE = False
_INSTALLED = False
_REG_LOCK = _REAL["Lock"]()
_TL = threading.local()

# site name -> acquire count
_LOCKS: Dict[str, int] = {}
# (a, b) -> count: b acquired while a held
_EDGES: Dict[Tuple[str, str], int] = {}
_EDGE_STACKS: Dict[Tuple[str, str], str] = {}
_SAME_SITE: Dict[str, int] = {}
_VIOLATIONS: List[dict] = []
_SEEN_PAIRS: set = set()
_SEEN_MUTATIONS: set = set()


def _held() -> list:
    held = getattr(_TL, "held", None)
    if held is None:
        held = _TL.held = []
    return held


def _rlock_counts() -> dict:
    counts = getattr(_TL, "rlock_counts", None)
    if counts is None:
        counts = _TL.rlock_counts = {}
    return counts


def _stack(skip: int = 3) -> str:
    frames = traceback.extract_stack()[:-skip]
    keep = [f for f in frames if "lockaudit" not in f.filename][-8:]
    return "".join(traceback.format_list(keep)).rstrip()


def _record_acquire_intent(lock: "_AuditedBase",
                           blocking: bool) -> None:
    if not _ACTIVE:
        return
    held = _held()
    with _REG_LOCK:
        _LOCKS[lock.name] = _LOCKS.get(lock.name, 0) + 1
        for h in held:
            if h is lock:
                if not lock.reentrant and blocking:
                    _VIOLATIONS.append({
                        "kind": "self-deadlock", "lock": lock.name,
                        "stack": _stack()})
                continue
            if h.name == lock.name:
                _SAME_SITE[lock.name] = \
                    _SAME_SITE.get(lock.name, 0) + 1
                continue
            edge = (h.name, lock.name)
            _EDGES[edge] = _EDGES.get(edge, 0) + 1
            if edge not in _EDGE_STACKS:
                _EDGE_STACKS[edge] = _stack()
            rev = (lock.name, edge[0])
            pair = tuple(sorted((edge[0], edge[1])))
            if rev in _EDGES and pair not in _SEEN_PAIRS:
                _SEEN_PAIRS.add(pair)
                _VIOLATIONS.append({
                    "kind": "inversion",
                    "pair": list(pair),
                    "stack_forward": _EDGE_STACKS.get(rev, ""),
                    "stack_reverse": _EDGE_STACKS[edge]})


class _AuditedBase:
    reentrant = False

    def __init__(self, real, name: str):
        self._real = real
        self.name = name

    def __repr__(self):
        return f"<audited {type(self._real).__name__} {self.name}>"


class AuditedLock(_AuditedBase):
    """Wrapper over a non-reentrant lock with acquisition tracking."""

    def acquire(self, blocking: bool = True, timeout: float = -1):
        _record_acquire_intent(self, blocking)
        got = self._real.acquire(blocking, timeout)
        if got:
            _held().append(self)
        return got

    def release(self):
        self._real.release()
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break

    def locked(self):
        return self._real.locked()

    def _is_owned(self):
        # given to threading.Condition so wait() never needs the
        # try-acquire probe (which would look like a self-deadlock)
        return any(h is self for h in _held())

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class AuditedRLock(_AuditedBase):
    reentrant = True

    def acquire(self, blocking: bool = True, timeout: float = -1):
        counts = _rlock_counts()
        if counts.get(id(self), 0) == 0:
            _record_acquire_intent(self, blocking)
        got = self._real.acquire(blocking, timeout)
        if got:
            n = counts.get(id(self), 0)
            counts[id(self)] = n + 1
            if n == 0:
                _held().append(self)
        return got

    def release(self):
        self._real.release()
        counts = _rlock_counts()
        n = counts.get(id(self), 1) - 1
        if n <= 0:
            counts.pop(id(self), None)
            held = _held()
            for i in range(len(held) - 1, -1, -1):
                if held[i] is self:
                    del held[i]
                    break
        else:
            counts[id(self)] = n

    # the Condition protocol: full release for wait(), restore after
    def _release_save(self):
        state = self._real._release_save()
        count = _rlock_counts().pop(id(self), 0)
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break
        return (state, count)

    def _acquire_restore(self, saved):
        state, count = saved
        self._real._acquire_restore(state)
        if count:
            _rlock_counts()[id(self)] = count
            _held().append(self)

    def _is_owned(self):
        return _rlock_counts().get(id(self), 0) > 0

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


def held_by_current(lock) -> bool:
    """Exact for audited locks; best-effort (is it held by ANYONE)
    for raw primitives created before the audit armed."""
    if isinstance(lock, _AuditedBase):
        return lock._is_owned()
    probe = getattr(lock, "_is_owned", None)
    if probe is not None:        # raw RLock
        try:
            return bool(probe())
        except Exception:  # noqa: BLE001 — foreign lock type
            return True
    locked = getattr(lock, "locked", None)
    return bool(locked()) if locked is not None else True


# -- construction patching -------------------------------------------

def _site(depth: int = 2) -> Optional[str]:
    """Creation-site name for the lock, or None when the caller is
    outside this repository (stdlib locks stay raw)."""
    import sys
    frame = sys._getframe(depth)
    fname = frame.f_code.co_filename
    if not fname.startswith(_REPO_ROOT) or \
            f"{os.sep}analysis{os.sep}" in fname:
        return None
    rel = os.path.relpath(fname, _REPO_ROOT)
    return f"{rel}:{frame.f_lineno}"


def _make_lock():
    name = _site()
    real = _REAL["Lock"]()
    return real if name is None else AuditedLock(real, name)


def _make_rlock():
    name = _site()
    real = _REAL["RLock"]()
    return real if name is None else AuditedRLock(real, name)


def _make_condition(lock=None):
    name = _site()
    if name is None:
        return _REAL["Condition"](lock)
    if lock is None:
        lock = AuditedRLock(_REAL["RLock"](), name)
    return _REAL["Condition"](lock)


def make_lock(name: str) -> AuditedLock:
    """Explicitly-named audited lock (tests, guards)."""
    return AuditedLock(_REAL["Lock"](), name)


def install() -> None:
    """Arm the audit: locks created from repo code are wrapped."""
    global _ACTIVE, _INSTALLED
    _ACTIVE = True
    if _INSTALLED:
        return
    threading.Lock = _make_lock
    threading.RLock = _make_rlock
    threading.Condition = _make_condition
    _INSTALLED = True


def uninstall() -> None:
    """Disarm: restore the raw constructors and stop recording.
    Already-wrapped locks keep working (bookkeeping only)."""
    global _ACTIVE, _INSTALLED
    _ACTIVE = False
    if _INSTALLED:
        threading.Lock = _REAL["Lock"]
        threading.RLock = _REAL["RLock"]
        threading.Condition = _REAL["Condition"]
        _INSTALLED = False


def enabled() -> bool:
    return _ACTIVE


def reset() -> None:
    with _REG_LOCK:
        _LOCKS.clear()
        _EDGES.clear()
        _EDGE_STACKS.clear()
        _SAME_SITE.clear()
        _VIOLATIONS.clear()
        _SEEN_PAIRS.clear()
        _SEEN_MUTATIONS.clear()


# -- guarded shared stores -------------------------------------------

def _mutation(store_name: str, op: str, lock) -> None:
    if not _ACTIVE or held_by_current(lock):
        return
    stack = _stack()
    key = (store_name, stack.splitlines()[-1] if stack else op)
    with _REG_LOCK:
        if key in _SEEN_MUTATIONS:
            return
        _SEEN_MUTATIONS.add(key)
        _VIOLATIONS.append({
            "kind": "unguarded-mutation", "store": store_name,
            "op": op, "stack": stack})


class GuardedDict(dict):
    """dict that records a violation when mutated without the owning
    lock held.  default_factory preserves defaultdict semantics (a
    defaulting READ inserts, so it counts as a mutation too)."""

    def __init__(self, data, lock, name, default_factory=None):
        super().__init__(data)
        self._vtp_lock = lock
        self._vtp_name = name
        self._vtp_factory = default_factory

    def __missing__(self, key):
        if self._vtp_factory is None:
            raise KeyError(key)
        _mutation(self._vtp_name, "__missing__", self._vtp_lock)
        value = self._vtp_factory()
        super().__setitem__(key, value)
        return value

    def __setitem__(self, key, value):
        _mutation(self._vtp_name, "__setitem__", self._vtp_lock)
        super().__setitem__(key, value)

    def __delitem__(self, key):
        _mutation(self._vtp_name, "__delitem__", self._vtp_lock)
        super().__delitem__(key)

    def pop(self, *a, **kw):
        _mutation(self._vtp_name, "pop", self._vtp_lock)
        return super().pop(*a, **kw)

    def popitem(self):
        _mutation(self._vtp_name, "popitem", self._vtp_lock)
        return super().popitem()

    def clear(self):
        _mutation(self._vtp_name, "clear", self._vtp_lock)
        super().clear()

    def update(self, *a, **kw):
        _mutation(self._vtp_name, "update", self._vtp_lock)
        super().update(*a, **kw)

    def setdefault(self, *a, **kw):
        _mutation(self._vtp_name, "setdefault", self._vtp_lock)
        return super().setdefault(*a, **kw)


class GuardedOrderedDict(GuardedDict):
    def __init__(self, data, lock, name):
        # keep OrderedDict-only surface the server uses (move_to_end
        # emulated: plain dicts preserve insertion order, re-insert)
        super().__init__(data, lock, name)

    def move_to_end(self, key, last=True):
        _mutation(self._vtp_name, "move_to_end", self._vtp_lock)
        value = super(GuardedDict, self).pop(key)
        if last:
            dict.__setitem__(self, key, value)
        else:
            items = [(key, value)] + list(self.items())
            dict.clear(self)
            dict.update(self, items)

    def popitem(self, last=True):
        _mutation(self._vtp_name, "popitem", self._vtp_lock)
        key = next(reversed(self) if last else iter(self))
        return key, dict.pop(self, key)


def guard_store(container, lock, name):
    """Wrap a dict-like shared store so mutation without *lock* held
    is recorded.  Returns the wrapped store."""
    factory = getattr(container, "default_factory", None)
    import collections
    if isinstance(container, collections.OrderedDict):
        return GuardedOrderedDict(container, lock, name)
    return GuardedDict(container, lock, name,
                       default_factory=factory)


def maybe_guard_metrics(mod) -> None:
    """Arm the metrics registries (called by metrics.py at import
    when the audit env flag is set)."""
    if not _ACTIVE:
        return
    for attr in ("_observations", "_counters", "_gauges",
                 "_obs_totals"):
        setattr(mod, attr, guard_store(getattr(mod, attr),
                                       mod._lock, f"metrics.{attr}"))


def maybe_guard_server(state) -> None:
    """Arm the state server's lock-owned maps (called from
    StateServer.__init__ when the audit env flag is set)."""
    if not _ACTIVE:
        return
    state._leases = guard_store(state._leases, state._lock,
                                "state_server._leases")
    state._req_cache = guard_store(state._req_cache, state._lock,
                                   "state_server._req_cache")
    state._pod_chips = guard_store(state._pod_chips, state._lock,
                                   "state_server._pod_chips")
    state._chips_used = guard_store(state._chips_used, state._lock,
                                    "state_server._chips_used")


# -- reporting -------------------------------------------------------

def _cycles(edges) -> List[List[str]]:
    """Distinct simple cycles (length >= 2) in the site digraph,
    deduped by node set; bounded depth keeps this a report-time
    convenience, not a solver."""
    graph: Dict[str, List[str]] = {}
    for a, b in edges:
        graph.setdefault(a, []).append(b)
    found: List[List[str]] = []
    seen_sets = set()

    def dfs(start: str, node: str, path: List[str]):
        if len(path) > 6:
            return
        for nxt in graph.get(node, ()):
            if nxt == start and len(path) >= 2:
                key = frozenset(path)
                if key not in seen_sets:
                    seen_sets.add(key)
                    found.append(list(path))
            elif nxt not in path:
                dfs(start, nxt, path + [nxt])

    for start in sorted(graph):
        dfs(start, start, [start])
    return found


def report() -> dict:
    with _REG_LOCK:
        edges = dict(_EDGES)
        doc = {
            "pid": os.getpid(),
            "locks": dict(sorted(_LOCKS.items())),
            "edges": sorted(
                [[a, b, n] for (a, b), n in edges.items()]),
            "same_site_nestings": dict(sorted(_SAME_SITE.items())),
            "violations": list(_VIOLATIONS),
        }
    doc["cycles"] = _cycles(edges)
    return doc


def flush(path: Optional[str] = None) -> Optional[str]:
    """Write the report atomically; returns the path written."""
    out_dir = path or os.environ.get(ENV_OUT, "")
    if not out_dir:
        return None
    import json
    os.makedirs(out_dir, exist_ok=True)
    fpath = os.path.join(out_dir, f"lockaudit-{os.getpid()}.json")
    tmp = fpath + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(report(), f, indent=1, default=str)
    os.replace(tmp, fpath)
    return fpath


def install_from_env() -> None:
    """Arm from VTP_LOCK_AUDIT (called by volcano_tpu/__init__ before
    any repo lock exists).  With VTP_LOCK_AUDIT_OUT set, the report
    is flushed at exit AND every 500ms from a daemon thread, so a
    SIGKILL'd process (the chaos conductor reboots servers that way)
    still leaves its last graph on disk."""
    if not os.environ.get(ENV_FLAG):
        return
    install()
    if not os.environ.get(ENV_OUT):
        return
    import atexit
    atexit.register(flush)
    # SIGTERM bypasses atexit, and the chaos conductor tears the
    # plane down with exactly that — so a violation recorded after
    # the last 2Hz flush would vanish with the process.  Flush once
    # from the handler, then hand the signal back to the previous
    # disposition so shutdown semantics stay untouched.
    import signal
    prev = signal.getsignal(signal.SIGTERM)

    def _flush_on_term(signum, frame):
        try:
            flush()
        except OSError:
            # vtplint: disable=except-pass (mid-shutdown best effort; the 2Hz flusher already wrote a near-final report)
            pass
        if callable(prev) and prev not in (signal.SIG_DFL,
                                           signal.SIG_IGN):
            prev(signum, frame)
        else:
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

    try:
        signal.signal(signal.SIGTERM, _flush_on_term)
    except ValueError:
        # vtplint: disable=except-pass (not the main thread: signal registration is impossible, the 2Hz flusher remains the fallback)
        pass

    def _flusher():
        import time
        while True:
            time.sleep(0.5)
            try:
                flush()
            except OSError:
                # vtplint: disable=except-pass (2Hz best-effort report flusher; the atexit flush is the authoritative write)
                pass

    threading.Thread(target=_flusher, name="lockaudit-flush",
                     daemon=True).start()
