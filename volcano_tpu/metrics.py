"""Prometheus-style metrics registry (reference: pkg/scheduler/metrics).

Zero-dependency: counters, gauges and summary histograms kept in-process
with a text exposition dump, so the benchmark harness and tests can
assert on scheduling latencies the same way the reference scrapes
e2e_scheduling_latency_milliseconds.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict, List, Tuple

_lock = threading.Lock()
_observations: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], List[float]] = \
    defaultdict(list)
_counters: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = \
    defaultdict(float)
_gauges: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
# Cumulative (count, sum) per summary series: the exposition must stay
# monotonic even though the quantile window below is trimmed, or
# scrapers' rate()/increase() see phantom counter resets.
_obs_totals: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                  Tuple[int, float]] = defaultdict(lambda: (0, 0.0))


def _key(name: str, labels: dict) -> Tuple[str, Tuple[Tuple[str, str], ...]]:
    return name, tuple(sorted(labels.items()))


# Per-series retention cap: summaries keep a sliding window so a
# long-running daemon emitting per-task latencies can't grow without
# bound (the exposition reports count/sum over the window).
MAX_OBSERVATIONS = 16384

# Lock-order audit opt-in (analysis/lockaudit.py): when armed, the
# four registries are wrapped so any mutation without _lock held is
# recorded as a violation — every writer in this module must stay
# inside `with _lock`, and this makes the rule mechanical.
import os as _os

if _os.environ.get("VTP_LOCK_AUDIT"):
    import sys as _sys

    from volcano_tpu.analysis import lockaudit as _lockaudit
    _lockaudit.maybe_guard_metrics(_sys.modules[__name__])


def observe(name: str, value: float, **labels):
    with _lock:
        key = _key(name, labels)
        series = _observations[key]
        series.append(value)
        count, total = _obs_totals[key]
        _obs_totals[key] = (count + 1, total + value)
        if len(series) > MAX_OBSERVATIONS:
            del series[:len(series) // 2]


def inc(name: str, value: float = 1.0, **labels):
    with _lock:
        _counters[_key(name, labels)] += value


def set_gauge(name: str, value: float, **labels):
    """Point-in-time value (e.g. current unschedulable-job count)."""
    with _lock:
        _gauges[_key(name, labels)] = value


def get_gauge(name: str, **labels) -> float:
    with _lock:
        return _gauges.get(_key(name, labels), 0.0)


def clear_gauge_series(name: str):
    """Drop every labeled gauge of *name* — used before re-exporting a
    per-object family (e.g. job_share) so objects that disappeared
    don't linger as stale series (reference metrics/job.go delete)."""
    with _lock:
        for key in [k for k in _gauges if k[0] == name]:
            del _gauges[key]


def delete_labeled(**labels):
    """Drop every series (gauge/counter/summary) carrying ALL of the
    given labels — the analogue of the reference's per-object metric
    deletion when a job/queue is removed (metrics/job.go)."""
    match = set(labels.items())
    with _lock:
        for store in (_gauges, _counters, _observations, _obs_totals):
            for key in [k for k in store if match <= set(k[1])]:
                del store[key]


def swap_gauge_families(families, rows, **scope):
    """Atomically replace whole gauge families: under ONE lock, drop
    every existing series whose metric name is in *families* (one scan
    of the registry), then install *rows* ([(name, labels-dict, value)]).
    A concurrent /metrics scrape sees either the old or the new export,
    never a half-cleared family.

    *scope* labels narrow the drop to series carrying ALL of them —
    how per-node exporters (several node agents in one process, e.g.
    the bandwidth families) replace only THEIR slice of a family
    instead of clobbering each other's every sync."""
    families = set(families)
    match = set(scope.items())
    with _lock:
        for key in [k for k in _gauges
                    if k[0] in families and match <= set(k[1])]:
            del _gauges[key]
        for name, labels, value in rows:
            _gauges[_key(name, labels)] = value


def resource_gauge_rows(prefix: str, res, **labels):
    """Rows for one resource vector in the reference's per-dimension
    queue gauge shape: <prefix>_milli_cpu, <prefix>_memory_bytes, and
    <prefix>_scalar_resources{resource=...} per scalar dimension
    (metrics/queue.go).  Feed to swap_gauge_families."""
    rows = [(f"{prefix}_milli_cpu", dict(labels), res.milli_cpu),
            (f"{prefix}_memory_bytes", dict(labels), res.memory)]
    for dim, val in res.res.items():
        if dim in ("cpu", "memory", "pods"):
            continue
        rows.append((f"{prefix}_scalar_resources",
                     dict(labels, resource=dim), val))
    return rows


def get_observations(name: str, **labels) -> List[float]:
    with _lock:
        return list(_observations.get(_key(name, labels), []))


def get_counter(name: str, **labels) -> float:
    with _lock:
        return _counters.get(_key(name, labels), 0.0)


def quantile(name: str, q: float, **labels) -> float:
    obs = sorted(get_observations(name, **labels))
    if not obs:
        return 0.0
    idx = min(len(obs) - 1, int(q * len(obs)))
    return obs[idx]


def reset():
    with _lock:
        _observations.clear()
        _counters.clear()
        _gauges.clear()
        _obs_totals.clear()


def write_exposition(handler) -> None:
    """Write the Prometheus text exposition as an HTTP response on a
    BaseHTTPRequestHandler (shared by serve() and the state server)."""
    body = dump().encode()
    handler.send_response(200)
    handler.send_header("Content-Type", "text/plain; version=0.0.4")
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


def serve(port: int = 0):
    """Expose /metrics over HTTP (Prometheus scrape endpoint analogue;
    reference: per-binary Prometheus registries).  Returns the server —
    call .shutdown() to stop; port 0 picks a free port
    (server.server_address[1])."""
    import http.server
    import threading

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - stdlib API
            if self.path != "/metrics":
                self.send_response(404)
                self.end_headers()
                return
            write_exposition(self)

        def log_message(self, *args):  # quiet
            pass

    server = http.server.ThreadingHTTPServer(("127.0.0.1", port), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server


def _escape_label(value) -> str:
    """Prometheus text-format label-value escaping (the exposition
    format's only three escapes): a node name or free-text reason
    carrying a quote, backslash or newline must not corrupt the
    scrape output."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _label_str(labels) -> str:
    return ",".join(f'{k}="{_escape_label(v)}"' for k, v in labels)


def dump() -> str:
    """Prometheus text exposition."""
    lines = []
    with _lock:
        for (name, labels), value in sorted(_gauges.items()):
            lbl = _label_str(labels)
            lines.append(f"{name}{{{lbl}}} {value}" if lbl
                         else f"{name} {value}")
        for (name, labels), value in sorted(_counters.items()):
            lbl = _label_str(labels)
            lines.append(f"{name}{{{lbl}}} {value}" if lbl
                         else f"{name} {value}")
        for (name, labels), obs in sorted(_observations.items()):
            lbl = _label_str(labels)
            suffix = f"{{{lbl}}}" if lbl else ""
            count, total = _obs_totals[(name, labels)]
            lines.append(f"{name}_count{suffix} {count}")
            lines.append(f"{name}_sum{suffix} {total}")
    return "\n".join(lines) + "\n"
