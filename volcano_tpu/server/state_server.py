"""HTTP/JSON state server — the apiserver analogue.

This is the wire boundary the reference control plane is built around:
scheduler, controller manager, agent scheduler and node agents run as
separate OS processes and coordinate ONLY through this server, the way
the reference components only meet at the Kubernetes apiserver
(pkg/scheduler/cache/cache.go:109 informer wiring, cache.go:984 bind
POST, event_handlers.go watch dispatch).

Design:
  * The authoritative store is a FakeCluster (same semantics in-process
    and served — one implementation of truth).  The admission chain
    runs server-side on create, like real webhooks at the apiserver.
  * Every mutation appends to a monotonically-versioned event log; GET
    /watch?since=rv long-polls it.  Clients that fall off the ring
    re-list (resync), mirroring k8s watch/"too old resource version".
  * Leases implement leader election (cmd/scheduler/app/server.go:99):
    compare-and-swap on {name, holder, ttl} under the server lock.
  * POST /tick advances the simulated kubelet (Bound->Running,
    Releasing->deleted), or --tick-period makes the server self-tick.

Stdlib-only (ThreadingHTTPServer + urllib on the client side).
"""

from __future__ import annotations

import collections
import itertools
import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from volcano_tpu.api import codec
from volcano_tpu.cache.fake_cluster import FakeCluster
from volcano_tpu.cache.kinds import KINDS

log = logging.getLogger(__name__)

EVENT_RING = 100_000     # events kept for watchers before forcing resync
AUDIT_RING = 200_000     # audit records kept for the latency exporter


def _error_code(e: Exception) -> int:
    """Exception -> wire status, the same mapping do_POST applies to
    whole-request failures (reused for per-item /bind_batch verdicts):
    missing object 404, admission veto 422, conflict 409, else 500."""
    if isinstance(e, KeyError):
        return 404
    if isinstance(e, ValueError):
        from volcano_tpu.webhooks.admission import AdmissionError
        return 422 if isinstance(e, AdmissionError) else 409
    return 500


class Lease:
    __slots__ = ("holder", "expires")

    def __init__(self, holder: str, expires: float):
        self.holder = holder
        self.expires = expires


class StateServer:
    """Owns the authoritative store + event log + leases."""

    def __init__(self, cluster: Optional[FakeCluster] = None):
        if cluster is None:
            from volcano_tpu.webhooks import default_admission
            cluster = FakeCluster()
            cluster.admission = default_admission()
        self.cluster = cluster
        # incarnation token: rv counters reset on restart, so clients
        # must detect a different server lifetime and re-list — an rv
        # ordering check alone misses a restarted server whose counter
        # has already passed the client's position
        import uuid
        self.epoch = uuid.uuid4().hex[:12]
        self._lock = threading.Lock()          # event log + leases
        self._event_cv = threading.Condition(self._lock)
        self._events: collections.deque = collections.deque(maxlen=EVENT_RING)
        self._rv = 0
        self._leases: Dict[str, Lease] = {}
        # audit trail: wall-clock-stamped mutation records, the
        # apiserver-audit-log analogue the latency exporter scrapes
        # (reference third_party/kube-apiserver-audit-exporter derives
        # pods/binding latency from audit timestamps).  Lazily enabled
        # by the first GET /audit so deployments that never poll pay
        # nothing on the mutation hot path.
        self._audit: collections.deque = collections.deque(maxlen=AUDIT_RING)
        self._audit_idx = 0
        self._audit_enabled = False
        cluster.watch(self._on_store_event)

    # -- event log -----------------------------------------------------

    def _on_store_event(self, kind: str, obj) -> None:
        try:
            payload = codec.encode(obj)
        except TypeError:
            log.exception("unencodable %s event dropped", kind)
            return
        with self._event_cv:
            self._rv += 1
            self._events.append((self._rv, kind, payload))
            if self._audit_enabled:
                self._audit_idx += 1
                self._audit.append(self._audit_record(
                    self._audit_idx, kind, obj))
            self._event_cv.notify_all()

    @staticmethod
    def _audit_record(idx: int, kind: str, obj) -> dict:
        rec = {"i": idx, "ts": time.time(), "kind": kind,
               "key": getattr(obj, "key", None) or
               (obj.get("key") if isinstance(obj, dict) else None)}
        # the two signals the latency exporter needs: pod binding
        # (node set) and job completion (phase terminal)
        node = getattr(obj, "node_name", None)
        if node is not None:
            rec["node"] = node
        phase = getattr(obj, "phase", None)
        if phase is not None:
            rec["phase"] = getattr(phase, "value", str(phase))
        return rec

    def audit_since(self, since: int, limit: int = 10_000,
                    key: str = "") -> Tuple[int, List[dict], bool]:
        """(idx, records with index > since, lost) — no long-poll, the
        exporter pages with `since` until a short batch comes back.
        The first call enables collection.  lost is True when the
        client's position fell off the ring (records were evicted
        unseen) — like events_since's resync signal.  limit bounds the
        copy made under the store lock so a lagging exporter can't
        stall mutations for a 200k-record copy."""
        with self._event_cv:
            self._audit_enabled = True
            if not self._audit:
                return self._audit_idx, [], False
            first = self._audit[0]["i"]
            lost = since < first - 1
            start = max(0, since - first + 1)
            records = list(itertools.islice(
                self._audit, start, start + max(1, limit)))
            idx = records[-1]["i"] if records else self._audit_idx
            if key:
                # server-side object filter (pod describe): paging
                # indices stay ring-global, only matching records ship
                records = [r for r in records if r.get("key") == key]
            return idx, records, lost

    def events_since(self, since: int, timeout: float = 25.0):
        """(rv, events, resync) — blocks up to timeout for news."""
        deadline = time.monotonic() + timeout
        with self._event_cv:
            while True:
                if self._events and self._events[0][0] > since + 1:
                    # client fell off the ring: it must re-list
                    return self._rv, [], True
                if self._rv > since and self._events:
                    # rvs are contiguous: the suffix starts at a known
                    # offset — never scan the whole (up to 100k) ring
                    start = since - self._events[0][0] + 1
                    news = list(itertools.islice(
                        self._events, max(0, start), None))
                    return self._rv, news, False
                remain = deadline - time.monotonic()
                if remain <= 0:
                    return self._rv, [], False
                self._event_cv.wait(remain)

    def snapshot_payload(self) -> dict:
        """Full store dump + current rv (client list+watch bootstrap)."""
        with self._event_cv:
            rv = self._rv
            stores = {}
            with self.cluster._lock:
                for kind, spec in KINDS.items():
                    store = getattr(self.cluster, spec.attr, {})
                    stores[kind] = {k: codec.encode(v)
                                    for k, v in store.items()}
                stores["_commands"] = codec.encode(
                    list(self.cluster.commands))
        return {"rv": rv, "stores": stores, "epoch": self.epoch}

    # -- leases (leader election) --------------------------------------

    def lease(self, name: str, holder: str, ttl: float,
              release: bool = False) -> dict:
        now = time.time()
        with self._lock:
            cur = self._leases.get(name)
            if release:
                if cur and cur.holder == holder:
                    del self._leases[name]
                return {"acquired": False, "holder": "", "expires": 0}
            if cur is None or cur.expires < now or cur.holder == holder:
                self._leases[name] = Lease(holder, now + ttl)
                return {"acquired": True, "holder": holder,
                        "expires": now + ttl}
            return {"acquired": False, "holder": cur.holder,
                    "expires": cur.expires}


class _Handler(BaseHTTPRequestHandler):
    server_version = "volcano-tpu-state"
    protocol_version = "HTTP/1.1"
    state: StateServer = None          # injected by serve()
    token: str = ""                    # bearer token, all data routes

    # quiet the default stderr access log
    def log_message(self, fmt, *args):  # noqa: N802
        log.debug("http: " + fmt, *args)

    def _authorized(self) -> bool:
        """Every data route — reads included — requires the cluster
        bearer token when one is configured (VERDICT r4 weak #4: an
        open LIST/WATCH hands any peer the whole cluster state).
        Only /healthz (liveness probes can't carry credentials) and
        /metrics (Prometheus scrape; the generated scrape config
        carries the token, but an operator pointing a stock scraper
        at it must not lose telemetry) stay anonymous."""
        from volcano_tpu.server.tlsutil import token_ok
        if token_ok(self.token, self.headers.get("Authorization")):
            return True
        self._json(401, {"error": "missing or invalid bearer token"})
        return False

    def _json(self, code: int, payload) -> None:
        from volcano_tpu.server.httputil import json_response
        json_response(self, code, payload)

    def _body(self) -> dict:
        length = int(self.headers.get("Content-Length") or 0)
        if not length:
            return {}
        return json.loads(self.rfile.read(length))

    # -- GET -----------------------------------------------------------

    def do_GET(self):  # noqa: N802
        url = urlparse(self.path)
        st = self.state
        if url.path == "/healthz":
            return self._json(200, {"ok": True})
        if url.path == "/metrics":
            from volcano_tpu import metrics
            return metrics.write_exposition(self)
        if not self._authorized():
            return None
        if url.path == "/snapshot":
            return self._json(200, st.snapshot_payload())
        if url.path == "/leases":
            now = time.time()
            with st._lock:
                return self._json(200, {
                    name: {"holder": l.holder,
                           "expires_in": round(l.expires - now, 3)}
                    for name, l in st._leases.items()})
        if url.path == "/watch":
            # timeout=0 doubles as the DELTA RESYNC lane: the events
            # since a revision, returned immediately — a mirror whose
            # rv is still inside the event ring catches up in O(churn)
            # instead of re-LISTing; resync=true means the revision
            # fell off the compaction horizon (the ring) and only a
            # full /snapshot recovers
            q = parse_qs(url.query)
            since = int(q.get("since", ["0"])[0])
            timeout = min(float(q.get("timeout", ["25"])[0]), 55.0)
            rv, events, resync = st.events_since(since, timeout)
            return self._json(200, {
                "rv": rv, "resync": resync, "epoch": st.epoch,
                "events": [{"rv": r, "kind": k, "obj": o}
                           for r, k, o in events]})
        if url.path == "/bandwidth":
            # per-node DCN accounting reports (api/netusage.py), the
            # GET-route view of what the agents measured; ?node=
            # narrows to one host
            q = parse_qs(url.query)
            want = q.get("node", [""])[0]
            with st.cluster._lock:
                reports = {
                    name: codec.encode(rep) for name, rep in
                    getattr(st.cluster, "bandwidthreports", {}).items()
                    if not want or name == want}
            return self._json(200, {"reports": reports})
        if url.path == "/audit":
            q = parse_qs(url.query)
            since = int(q.get("since", ["0"])[0])
            key = q.get("key", [""])[0]
            idx, records, lost = st.audit_since(since, key=key)
            return self._json(200, {"idx": idx, "records": records,
                                    "lost": lost})
        return self._json(404, {"error": f"no route {url.path}"})

    # -- POST ----------------------------------------------------------

    def do_POST(self):  # noqa: N802
        if not self._authorized():
            return None
        url = urlparse(self.path)
        st = self.state
        cl = st.cluster
        try:
            body = self._body()
        except (ValueError, json.JSONDecodeError) as e:
            return self._json(400, {"error": str(e)})
        try:
            if url.path.startswith("/objects/"):
                kind = url.path[len("/objects/"):]
                if kind not in KINDS:
                    return self._json(404, {"error": f"unknown kind {kind}"})
                obj = codec.decode(body["obj"])
                key = body.get("key")
                stored = cl.put_object(kind, obj, key=key)
                return self._json(200, {"obj": codec.encode(stored)})
            if url.path == "/bind":
                cl.bind_pod(body["namespace"], body["name"],
                            body["node_name"])
                return self._json(200, {"ok": True})
            if url.path == "/bind_batch":
                # a gang's binds as ONE request (the wire fast lane's
                # biggest round-trip saving: 256 POSTs -> 1).  Failure
                # stays per-item — same verdict the per-pod route
                # would have returned, so a conflict on one pod never
                # vetoes its gang-mates
                results = []
                bound = 0
                for b in body.get("binds", []):
                    try:
                        cl.bind_pod(b["namespace"], b["name"],
                                    b["node_name"])
                        results.append({"ok": True})
                        bound += 1
                    except Exception as e:  # noqa: BLE001 — per-item
                        results.append({
                            "ok": False, "code": _error_code(e),
                            "error": str(e) or type(e).__name__})
                return self._json(200, {"bound": bound,
                                        "results": results})
            if url.path == "/evict":
                cl.evict_pod(body["namespace"], body["name"],
                             body.get("reason", ""))
                return self._json(200, {"ok": True})
            if url.path == "/nominate":
                cl.nominate_pod(body["namespace"], body["name"],
                                body["node_name"])
                return self._json(200, {"ok": True})
            if url.path == "/podgroup_status":
                cl.update_podgroup_status(codec.decode(body["obj"]))
                return self._json(200, {"ok": True})
            if url.path == "/record_event":
                cl.record_event(body["obj_key"], body["reason"],
                                body.get("message", ""))
                return self._json(200, {"ok": True})
            if url.path == "/command":
                cl.add_command(body["target"], body["action"])
                return self._json(200, {"ok": True})
            if url.path == "/drain_commands":
                cmds = cl.drain_commands(body["target"])
                return self._json(200, {"commands": cmds})
            if url.path == "/lease":
                return self._json(200, st.lease(
                    body["name"], body["holder"],
                    float(body.get("ttl", 15.0)),
                    release=bool(body.get("release"))))
            if url.path == "/tick":
                cl.tick()
                return self._json(200, {"ok": True})
            if url.path == "/complete_pod":
                cl.complete_pod(body["key"],
                                succeeded=bool(body.get("succeeded", True)),
                                exit_code=body.get("exit_code"))
                return self._json(200, {"ok": True})
            return self._json(404, {"error": f"no route {url.path}"})
        except KeyError as e:
            return self._json(404, {"error": str(e)})
        except ValueError as e:
            # discriminate by TYPE, never message wording (see
            # _error_code): admission veto 422, conflict 409
            return self._json(_error_code(e), {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — surface, don't kill thread
            log.exception("POST %s failed", url.path)
            return self._json(500, {"error": str(e)})

    # -- DELETE --------------------------------------------------------

    def do_DELETE(self):  # noqa: N802
        if not self._authorized():
            return None
        url = urlparse(self.path)
        if not url.path.startswith("/objects/"):
            return self._json(404, {"error": f"no route {url.path}"})
        kind = url.path[len("/objects/"):]
        if kind not in KINDS:
            return self._json(404, {"error": f"unknown kind {kind}"})
        key = parse_qs(url.query).get("key", [""])[0]
        if not key:
            return self._json(400, {"error": "missing key"})
        self.state.cluster.delete_object(kind, key)
        return self._json(200, {"ok": True})


def serve(port: int = 0, cluster: Optional[FakeCluster] = None,
          tick_period: float = 0.0, tls_cert: str = "",
          tls_key: str = "", token: str = ""
          ) -> Tuple[ThreadingHTTPServer, StateServer]:
    """Start the server on 127.0.0.1:port (0 = ephemeral); returns
    (http_server, state).  Caller runs http_server.serve_forever()
    or uses the background thread started here.  tls_cert/tls_key
    make the listener TLS-only; token guards every route except
    /healthz and /metrics."""
    from volcano_tpu.server.httputil import serve_threaded
    state = StateServer(cluster)
    httpd = serve_threaded(_Handler, {"state": state, "token": token},
                           port, "state-server",
                           tls_cert=tls_cert, tls_key=tls_key)
    state.tick_stop = threading.Event()
    if tick_period > 0:
        def tick_loop():
            while not state.tick_stop.wait(tick_period):
                try:
                    state.cluster.tick()
                except Exception:  # noqa: BLE001
                    log.exception("tick failed")
        threading.Thread(target=tick_loop, name="kubelet-tick",
                         daemon=True).start()
    return httpd, state
