"""HTTP/JSON state server — the apiserver analogue.

This is the wire boundary the reference control plane is built around:
scheduler, controller manager, agent scheduler and node agents run as
separate OS processes and coordinate ONLY through this server, the way
the reference components only meet at the Kubernetes apiserver
(pkg/scheduler/cache/cache.go:109 informer wiring, cache.go:984 bind
POST, event_handlers.go watch dispatch).

Design:
  * The authoritative store is a FakeCluster (same semantics in-process
    and served — one implementation of truth).  The admission chain
    runs server-side on create, like real webhooks at the apiserver.
  * Every mutation appends to a monotonically-versioned event log; GET
    /watch?since=rv long-polls it.  Clients that fall off the ring
    re-list (resync), mirroring k8s watch/"too old resource version".
  * Leases implement leader election (cmd/scheduler/app/server.go:99):
    compare-and-swap on {name, holder, ttl} under the server lock.
  * POST /tick advances the simulated kubelet (Bound->Running,
    Releasing->deleted), or --tick-period makes the server self-tick.

Stdlib-only (ThreadingHTTPServer + urllib on the client side).
"""

from __future__ import annotations

import collections
import itertools
import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from volcano_tpu.api import codec
from volcano_tpu.cache.fake_cluster import FakeCluster
from volcano_tpu.cache.kinds import KINDS

log = logging.getLogger(__name__)

EVENT_RING = 100_000     # events kept for watchers before forcing resync
AUDIT_RING = 200_000     # audit records kept for the latency exporter
TRACE_RING = 512         # kept scheduler session traces (GET /traces)


def _error_code(e: Exception) -> int:
    """Exception -> wire status, the same mapping do_POST applies to
    whole-request failures (reused for per-item /bind_batch verdicts):
    missing object 404, admission veto 422, conflict 409, read-only
    degrade 503, else 500."""
    from volcano_tpu.server.durability import ReadOnlyError
    if isinstance(e, ReadOnlyError):
        return 503
    if isinstance(e, KeyError):
        return 404
    if isinstance(e, ValueError):
        from volcano_tpu.webhooks.admission import AdmissionError
        return 422 if isinstance(e, AdmissionError) else 409
    return 500


# what a read-only (degraded) server still answers on POST: leases
# (leader election must keep working through a full disk — in-memory,
# journaling resumes at heal) and traces (never durable anyway).
# Everything else mutates the store and CANNOT be made durable, so it
# gets 503 + Retry-After instead of an un-durable ack.
READONLY_OK_POSTS = frozenset({"/lease", "/trace"})
RETRY_AFTER_S = 1


class Lease:
    # expires is a MONOTONIC-clock deadline: a wall-clock jump (NTP
    # step, VM resume) can neither mass-expire live leases nor
    # immortalize a dead holder's (found while making leases durable —
    # a wall deadline replayed after downtime did both).
    # term is the fencing token: a per-name counter that bumps on
    # every acquisition that is not a live same-holder renewal, so
    # two holders can never share a term and a deposed holder's
    # writes are refusable by comparison alone
    __slots__ = ("holder", "expires", "term")

    def __init__(self, holder: str, expires: float, term: int = 0):
        self.holder = holder
        self.expires = expires
        self.term = term


class StateServer:
    """Owns the authoritative store + event log + leases."""

    def __init__(self, cluster: Optional[FakeCluster] = None,
                 durable=None, replication=None):
        self.durable = durable                 # DurableStore or None
        self.repl = replication                # replication.Replication
        recovery = None
        if durable is not None:
            recovery = getattr(durable, "recovery", None)
            if recovery is None:
                recovery = durable.recover(event_ring=EVENT_RING)
            if recovery.cluster is not None:
                if cluster is not None and cluster is not recovery.cluster:
                    log.warning("durable state in %s takes precedence "
                                "over the seed cluster", durable.dir)
                cluster = recovery.cluster
        if cluster is None:
            from volcano_tpu.webhooks import default_admission
            cluster = FakeCluster()
            cluster.admission = default_admission()
        if getattr(cluster, "admission", None) is None and \
                recovery is not None and cluster is recovery.cluster:
            # a WAL-recovered store has no admission chain attached
            # (chains hold process-local callables); default unless
            # the caller swaps in a RemoteAdmission afterwards
            from volcano_tpu.webhooks import default_admission
            cluster.admission = default_admission()
        self.cluster = cluster
        # incarnation token: rv counters reset on restart, so clients
        # must detect a different server lifetime and re-list — an rv
        # ordering check alone misses a restarted server whose counter
        # has already passed the client's position.  Durable boots
        # keep the BASE and bump only the boot half ("BASE.BOOT"), so
        # mirrors know the rv history is WAL-continuous and may
        # delta-resync across the restart instead of re-listing.
        import uuid
        self.epoch = recovery.epoch if recovery is not None \
            else uuid.uuid4().hex[:12]
        self._lock = threading.Lock()          # event log + leases
        self._event_cv = threading.Condition(self._lock)
        self._events: collections.deque = collections.deque(maxlen=EVENT_RING)
        self._rv = 0
        self._leases: Dict[str, Lease] = {}
        # fencing substrate: per-name monotonic term counters (never
        # reissued, even after expiry/release — or a deposed holder
        # could reacquire "its" term) and per-name fence floors (the
        # highest term whose write this plane ever accepted; staler
        # writes 409).  Both are journaled and recovered.
        self._lease_terms: Dict[str, int] = {}
        self._fences: Dict[str, int] = {}
        # observability: per-fence-name count of refused stale writes
        self._fenced_counts: Dict[str, int] = {}
        # idempotency keys: req id -> (code, payload) of the response
        # already committed for that request — a client retrying a
        # mutation whose ack was lost in a crash/partition gets the
        # recorded verdict instead of double-applying
        self._req_cache: "collections.OrderedDict[str, tuple]" = \
            collections.OrderedDict()
        if recovery is not None:
            self._rv = recovery.rv
            self._events.extend(recovery.events)
            # vtplint: disable=wall-clock (disk carries wall expiries; rebased onto monotonic here)
            now_m, now_w = time.monotonic(), time.time()
            self._lease_terms.update(
                getattr(recovery, "lease_terms", None) or {})
            self._fences.update(getattr(recovery, "fences", None) or {})
            for name, (holder, exp_wall) in recovery.leases.items():
                # rebase the persisted wall expiry onto THIS boot's
                # monotonic clock: the remaining TTL is honoured, so a
                # restarted server refuses a second leader inside an
                # old holder's term.  A live lease's term is by
                # construction the max ever issued for its name.
                self._leases[name] = Lease(
                    holder, now_m + (exp_wall - now_w),
                    term=self._lease_terms.get(name, 0))
            self._req_cache.update(recovery.req_cache)
        # audit trail: wall-clock-stamped mutation records, the
        # apiserver-audit-log analogue the latency exporter scrapes
        # (reference third_party/kube-apiserver-audit-exporter derives
        # pods/binding latency from audit timestamps).  Lazily enabled
        # by the first GET /audit so deployments that never poll pay
        # nothing on the mutation hot path.
        self._audit: collections.deque = collections.deque(maxlen=AUDIT_RING)
        self._audit_idx = 0
        self._audit_enabled = False
        # chip-overcommit guard (found by tools/chaos_conductor.py:
        # under sustained ack-lost faults a scheduler whose bind acks
        # died un-assumes the gang, and its stale mirror re-allocates
        # chips the server already committed to another gang — the
        # trusted-scheduler design needs an apiserver-side backstop).
        # The maps are event-sourced: _on_store_event keeps them in
        # O(1) per pod/node event, so validation never scans the
        # store; _bind_mutex makes check-and-bind atomic across
        # concurrent handler threads.
        self._bind_mutex = threading.Lock()
        self._pod_chips: Dict[str, tuple] = {}   # pod key -> (node, chips)
        self._chips_used: Dict[str, float] = {}  # node -> bound chips
        self._node_chip_cap: Dict[str, float] = {}
        self._rebuild_chip_maps()
        # scheduler session traces (trace.py docs): in-memory ring,
        # deliberately NOT journaled — across a crash it resets
        # cleanly with the new epoch (clients see the epoch change and
        # know history restarted) and the posting scheduler refills it
        # within a few cycles; a trace is accepted only whole, so the
        # ring never serves half a tree
        self._traces: collections.deque = collections.deque(
            maxlen=TRACE_RING)
        # lock-order audit opt-in: wrap the _lock-owned maps so any
        # mutation without the lock held is recorded (the guard is
        # installed AFTER init — the single-threaded construction
        # above is exempt by construction)
        import os
        if os.environ.get("VTP_LOCK_AUDIT"):
            from volcano_tpu.analysis import lockaudit
            lockaudit.maybe_guard_server(self)
        cluster.watch(self._on_store_event)
        if self.repl is not None:
            if durable is None:
                raise ValueError("replication requires a durable "
                                 "store (--data-dir)")
            self.repl.attach(self)
        if durable is not None and recovery.cluster is None:
            # first boot of this data dir (possibly seeded from a
            # legacy --state file): the baseline must be durable
            # BEFORE the first ack, or a crash loses the seed
            self.write_snapshot()

    # -- event log -----------------------------------------------------

    def _rebuild_chip_maps(self) -> None:
        from volcano_tpu.api.resource import TPU
        from volcano_tpu.api.types import TaskStatus
        self._pod_chips.clear()
        self._chips_used.clear()
        self._node_chip_cap.clear()
        for name, node in self.cluster.nodes.items():
            cap = float((getattr(node, "allocatable", None) or {})
                        .get(TPU, 0) or 0)
            if cap > 0:
                self._node_chip_cap[name] = cap
        for key, pod in self.cluster.pods.items():
            if pod.node_name and pod.phase in (TaskStatus.BOUND,
                                               TaskStatus.RUNNING):
                chips = float(pod.resource_requests().get(TPU) or 0)
                if chips > 0:
                    self._pod_chips[key] = (pod.node_name, chips)
                    self._chips_used[pod.node_name] = \
                        self._chips_used.get(pod.node_name, 0.0) + chips

    def _track_chips(self, kind: str, obj) -> None:
        """O(1) per-event maintenance of the overcommit-guard maps
        (caller holds the event lock)."""
        from volcano_tpu.api.resource import TPU
        from volcano_tpu.api.types import TaskStatus
        if kind == "node":
            cap = float((getattr(obj, "allocatable", None) or {})
                        .get(TPU, 0) or 0)
            if cap > 0:
                self._node_chip_cap[obj.name] = cap
            else:
                self._node_chip_cap.pop(obj.name, None)
            return
        if kind == "node_deleted":
            self._node_chip_cap.pop(obj.name, None)
            return
        if kind not in ("pod", "pod_deleted"):
            # a podgroup/vcjob shares the ns/name key space: letting
            # its events touch the pod map would silently disarm the
            # guard on a key collision
            return
        key = getattr(obj, "key", None)
        if key is None:
            return
        old = self._pod_chips.pop(key, None)
        if old is not None:
            node, chips = old
            left = self._chips_used.get(node, 0.0) - chips
            if left > 1e-9:
                self._chips_used[node] = left
            else:
                self._chips_used.pop(node, None)
        if kind == "pod" and obj.node_name and \
                obj.phase in (TaskStatus.BOUND, TaskStatus.RUNNING):
            chips = float(obj.resource_requests().get(TPU) or 0)
            if chips > 0:
                self._pod_chips[key] = (obj.node_name, chips)
                self._chips_used[obj.node_name] = \
                    self._chips_used.get(obj.node_name, 0.0) + chips

    def check_bind_capacity(self, namespace: str, name: str,
                            node_name: str) -> Optional[str]:
        """The apiserver-side overcommit backstop: would binding this
        pod exceed the node's chip allocatable?  Returns the refusal
        message, or None when the bind is safe (re-binding a pod to
        the node it already occupies stays idempotent).  Callers hold
        _bind_mutex so check-and-bind is atomic."""
        from volcano_tpu.api.resource import TPU
        key = f"{namespace}/{name}"
        pod = self.cluster.pods.get(key)
        if pod is None:
            return None           # bind_pod will 404 with the details
        chips = float(pod.resource_requests().get(TPU) or 0)
        if chips <= 0:
            return None           # cpu-only pods are not chip-guarded
        return self._check_chip_capacity(
            key, node_name, chips, verb="bind",
            hint="stale scheduler view?")

    def _check_chip_capacity(self, key: str, node: str, chips: float,
                             verb: str, hint: str) -> Optional[str]:
        """The one chip-accounting core both guards share (/bind and
        the pod-PUT route must never diverge on the rule): replacing
        a pod's own booking on the same node is idempotent, anything
        else must fit under the node's allocatable.  Callers hold
        _bind_mutex; the map reads take _lock here."""
        with self._lock:
            cap = self._node_chip_cap.get(node)
            if cap is None:
                return None       # no chips on the node to guard
            used = self._chips_used.get(node, 0.0)
            prev = self._pod_chips.get(key)
            if prev is not None and prev[0] == node:
                used -= prev[1]   # replacing its own booking
            if used + chips > cap + 1e-9:
                return (f"{verb} overcommit: node {node} has "
                        f"{used:g}/{cap:g} chips bound; refusing "
                        f"+{chips:g} for {key} ({hint})")
        return None

    def check_put_capacity(self, obj) -> Optional[str]:
        """The overcommit backstop for WHOLE-POD writes: /bind and
        /bind_batch are capacity-guarded, but a pod object PUT via
        /objects/pod carrying node_name + Bound/Running used to land
        unchecked — so a stale mirror's delayed or reordered pod
        write could resurrect a drained pod onto chips the server
        had already re-bound (observed as a confirmed double-booking
        under the chaos conductor's reorder/duplicate faults with
        lock-audit timing).  Same shape as check_bind_capacity, but
        against the INCOMING object; replacing a pod's own booking on
        the same node stays idempotent.  Callers hold _bind_mutex."""
        from volcano_tpu.api.resource import TPU
        from volcano_tpu.api.types import TaskStatus
        node = getattr(obj, "node_name", None)
        if not node or getattr(obj, "phase", None) not in (
                TaskStatus.BOUND, TaskStatus.RUNNING):
            return None
        chips = float(obj.resource_requests().get(TPU) or 0)
        if chips <= 0:
            return None
        return self._check_chip_capacity(
            obj.key, node, chips, verb="put",
            hint=f"written as {obj.phase.value}; stale mirror write?")

    def _on_store_event(self, kind: str, obj) -> None:
        try:
            payload = codec.encode(obj)
        except TypeError:
            log.exception("unencodable %s event dropped", kind)
            return
        with self._event_cv:
            self._track_chips(kind, obj)
            self._rv += 1
            self._events.append((self._rv, kind, payload))
            if self.durable is not None:
                # journal under the same lock that assigned the rv so
                # WAL order == rv order; fsync happens in commit(),
                # on the ack path
                self.durable.append_event(self._rv, kind, payload)
            if self._audit_enabled:
                self._audit_idx += 1
                self._audit.append(self._audit_record(
                    self._audit_idx, kind, obj))
            self._event_cv.notify_all()

    # -- durability ----------------------------------------------------

    def _visible_rv(self) -> int:
        """Events are released to watchers/snapshots only once their
        WAL records are fsync'd: a mirror can then never hold an event
        a crash un-happens, which is what makes a delta resync across
        a restart exact (docs/design/durability.md).  Leading a
        replica group tightens the gate to the QUORUM horizon: an
        event only a doomed leader holds must never reach a mirror,
        or a promotion would un-happen state a mirror already saw."""
        if self.durable is None:
            return self._rv
        vis = min(self._rv, self.durable.synced_rv)
        if self.repl is not None and self.repl.is_leader:
            vis = min(vis, self.repl.quorum_rv())
        return vis

    def commit(self) -> None:
        """Durability barrier before an ack: fsync everything appended
        so far (group commit — one fsync covers concurrent handlers),
        then wake watchers gated on the synced horizon.  Leading a
        replica group, the barrier extends to the commit quorum: the
        ack waits until a majority holds the records durably — the
        wait doubling as the fence that stops a partitioned leader
        acking writes a promotion would lose.

        Raises durability.ReadOnlyError when the store is poisoned
        (failed fsync / full disk) or the replication quorum is lost:
        the caller must 503 instead of acking state that cannot be
        made durable."""
        if self.durable is None:
            return
        self.durable.commit()
        if self.repl is not None:
            self.repl.notify_durable()      # wake /wal long-polls
            self.repl.wait_quorum()         # leader only; may raise
        with self._event_cv:
            self._event_cv.notify_all()

    @property
    def readonly_reason(self) -> str:
        """Non-empty while the store is degraded to read-only."""
        if self.durable is None:
            return ""
        return self.durable.poisoned

    def try_heal(self) -> bool:
        """One heal attempt (fresh WAL segment + probe fsync + full
        snapshot); wakes watchers on success — the durable horizon
        jumped, releasing events stuck behind the poisoned WAL."""
        if self.durable is None or not self.durable.poisoned:
            return True
        if not self.durable.heal(self.disk_snapshot_doc):
            return False
        with self._event_cv:
            self._event_cv.notify_all()
        return True

    def disk_snapshot_doc(self) -> dict:
        """The on-disk snapshot: /snapshot payload + leases (wall-
        rebased) + the idempotency-key cache, so compaction of the WAL
        never drops what only the WAL knew."""
        doc = self.snapshot_payload()
        # vtplint: disable=wall-clock (the snapshot persists wall expiries by contract; monotonic deadlines rebased here)
        now_m, now_w = time.monotonic(), time.time()
        with self._lock:
            doc["leases"] = {
                n: {"holder": l.holder,
                    "expires_wall": now_w + (l.expires - now_m),
                    "term": l.term}
                for n, l in self._leases.items() if l.expires > now_m}
            # term counters + fence floors survive compaction even for
            # names with no live lease — monotonicity is the contract
            doc["lease_terms"] = dict(self._lease_terms)
            doc["fences"] = dict(self._fences)
            doc["req_cache"] = [
                {"id": i, "code": c, "resp": r}
                for i, (c, r) in self._req_cache.items()]
        return doc

    def write_snapshot(self) -> None:
        if self.durable is not None:
            self.durable.snapshot(self.disk_snapshot_doc)

    def replay_response(self, req_id: str):
        with self._lock:
            hit = self._req_cache.get(req_id)
            if hit is not None:
                self._req_cache.move_to_end(req_id)
            return hit

    def record_response(self, req_id: str, code: int, payload) -> None:
        from volcano_tpu.server.durability import REQ_CACHE
        with self._lock:
            self._req_cache[req_id] = (code, payload)
            while len(self._req_cache) > REQ_CACHE:
                self._req_cache.popitem(last=False)
        if self.durable is not None:
            # vtplint: disable=append-lock (_req records are keyed by unique id and replay idempotently: journal order does not matter, so the append deliberately runs outside _lock)
            self.durable.append({"k": "_req", "o": {
                "id": req_id, "code": code, "resp": payload}})

    def durability_status(self) -> dict:
        out = {"enabled": self.durable is not None,
               "epoch": self.epoch, "rv": self._rv,
               "visible_rv": self._visible_rv()}
        if self.durable is not None:
            out.update(self.durable.status())
        if self.repl is not None:
            out["replication"] = self.repl.status()
        return out

    # -- replication (server/replication.py) ---------------------------

    def replica_snapshot_doc(self) -> dict:
        """The follower-bootstrap payload: the full disk snapshot doc
        (stores + leases + req cache) plus the WAL seq horizon, term
        and epoch the tail resumes from.  The seq is read BEFORE the
        capture, so records appended during the capture overlap the
        doc — the follower skips store events at rv <= the doc's rv
        (the same rotated-then-snapshotted rule recovery applies) and
        the private record kinds replay idempotently.

        Only LOCAL durability is required here — never the commit
        quorum: a joining follower calls this to BECOME part of that
        quorum (waiting for it would deadlock the join)."""
        self.durable.commit()
        if self.repl is not None:
            self.repl.notify_durable()
        seq0 = self.durable.synced_seq
        doc = self.disk_snapshot_doc()
        doc["wal_seq"] = seq0
        doc["epoch"] = self.epoch
        if self.repl is not None:
            doc["term"] = self.repl.term
        return doc

    def install_replica_snapshot(self, doc: dict) -> None:
        """Follower full re-sync: replace store, event ring, leases,
        req cache and the local WAL wholesale with the leader's
        replica snapshot (reset_from_snapshot discards the local
        segments — the leader's history supersedes them)."""
        from volcano_tpu.server.durability import decode_stores_into
        from volcano_tpu.webhooks import default_admission
        epoch = doc.get("epoch") or self.epoch
        cluster = FakeCluster()
        decode_stores_into(cluster, doc.get("stores", {}))
        # keep THIS replica's configured admission (e.g. a
        # --webhook-url RemoteAdmission): a bootstrap replaces the
        # data, never the policy chain a promotion will enforce
        cluster.admission = getattr(self.cluster, "admission", None) \
            or default_admission()
        # vtplint: disable=wall-clock (bootstrap doc carries wall expiries; rebased onto monotonic here)
        now_m, now_w = time.monotonic(), time.time()
        # lock hierarchy: the compaction gate (_snap_lock) is the
        # OUTERMOST lock — snapshot()/heal() hold it while capturing
        # under the server lock, so taking it the other way around
        # here deadlocked a follower's tail thread against its own
        # wal-compactor (found by analysis/lockaudit.py; the gate is
        # acquired before the event lock precisely for this)
        with self.durable.snapshot_gate():
            with self._event_cv:
                self.durable.reset_from_snapshot(doc, epoch)
                cluster.watch(self._on_store_event)
                self.cluster = cluster
                self.epoch = epoch
                self._rv = int(doc.get("rv", 0))
                self._events.clear()
                self._leases.clear()
                self._lease_terms = {
                    n: int(t) for n, t in
                    (doc.get("lease_terms") or {}).items()}
                self._fences = {
                    n: int(t) for n, t in
                    (doc.get("fences") or {}).items()}
                for name, rec in (doc.get("leases") or {}).items():
                    exp_wall = float(rec["expires_wall"])
                    term = int(rec.get("term", 0))
                    self._lease_terms[name] = max(
                        self._lease_terms.get(name, 0), term)
                    if exp_wall > now_w:
                        self._leases[name] = Lease(
                            rec["holder"], now_m + (exp_wall - now_w),
                            term=term)
                self._req_cache.clear()
                for rec in (doc.get("req_cache") or []):
                    self._req_cache[rec["id"]] = (int(rec["code"]),
                                                  rec["resp"])
                self._rebuild_chip_maps()
                self._event_cv.notify_all()

    def mirror_ship(self, since_seq: int, timeout: float) -> dict:
        """The `/wal?mirror=1` lane: framed WAL records for a
        cross-region OBJECT MIRROR (federation/mirror.py).  Same frame
        + CRC + seq stream the replica tail consumes, with two
        deliberate differences from Replication.ship():

          * NON-QUORUM — the caller is never registered as a follower
            and its ack never counts toward the commit quorum: a
            mirror is a read cache at advertised staleness, and a
            distant region tailing the WAL must not be able to slow
            (or wedge) the source region's write acks.
          * DURABLE-ONLY — works on any durable server, replicated or
            not (a single-server lab region can be mirrored).

        Leading a replica group, shipped records are CAPPED at the
        quorum horizon: a mirror must never hold a record a leader
        failover could un-happen (the same gate _visible_rv applies
        to watchers).  On a follower the local synced prefix is
        served as-is — the mirror's contract is staleness, not
        quorum, and cutover correctness gates on the GLOBAL store."""
        from volcano_tpu.server.replication import SHIP_BATCH
        deadline = time.monotonic() + max(0.0, min(timeout, 30.0))
        while True:
            out = self.durable.ship_since(since_seq, limit=SHIP_BATCH)
            if self.repl is not None and self.repl.is_leader:
                q = self.repl.quorum_seq()
                if q < out["last_seq"]:
                    keep = max(0, q - since_seq)
                    out = {"records": out["records"][:keep],
                           "last_seq": max(since_seq, q),
                           "resync": out["resync"]}
            if out["records"] or out["resync"] or \
                    time.monotonic() >= deadline:
                break
            with self._event_cv:
                self._event_cv.wait(
                    min(0.5, max(0.01,
                                 deadline - time.monotonic())))
        return {"epoch": self.epoch, "rv": self._visible_rv(),
                "snapshot_rv": self.durable.snapshot_rv,
                "last_seq": out["last_seq"],
                "resync": out["resync"], "records": out["records"]}

    def apply_shipped(self, lines) -> None:
        """Fold one shipped batch into this follower: verify EVERY
        record's CRC + sequence first (a corrupt or torn shipped
        record refuses the whole batch — never a partial apply), then
        journal the leader-framed lines verbatim, apply them to the
        store/leases/req-cache, and fsync before the new rv becomes
        visible — the bounded-staleness contract: a follower never
        serves an rv it has not durably applied."""
        from volcano_tpu.server.durability import (apply_event_obj,
                                                   parse_record)
        from volcano_tpu.server.replication import \
            ShippedCorruptionError
        parsed = []
        seq = self.durable.synced_seq
        for line in lines:
            rec, bad = parse_record(line.rstrip("\n"))
            if rec is None:
                raise ShippedCorruptionError(
                    f"record after seq {seq}: {bad}")
            q = int(rec.get("q", 0))
            if q <= seq:
                continue                    # overlap re-ship: skip
            if q != seq + 1:
                raise ShippedCorruptionError(
                    f"sequence gap {seq}->{q}")
            seq = q
            parsed.append((line, q, rec))
        if not parsed:
            return
        with self._event_cv:
            snap_rv = self.durable.snapshot_rv
            for line, q, rec in parsed:
                kind = rec.get("k")
                erv = int(rec.get("rv", 0))
                self.durable.append_shipped(line, q, erv)
                if kind == "_probe":
                    continue
                if kind == "_lease":
                    o = rec["o"]
                    if o.get("term"):
                        self._lease_terms[o["name"]] = max(
                            self._lease_terms.get(o["name"], 0),
                            int(o["term"]))
                    if o.get("holder"):
                        # vtplint: disable=wall-clock (shipped record carries a wall expiry; rebased onto monotonic here)
                        self._leases[o["name"]] = Lease(
                            o["holder"], time.monotonic() +
                            # vtplint: disable=wall-clock (shipped wall expiry rebased)
                            (float(o["expires_wall"]) - time.time()),
                            term=int(o.get("term", 0)))
                    else:
                        self._leases.pop(o["name"], None)
                elif kind == "_fence":
                    o = rec["o"]
                    self._fences[o["name"]] = max(
                        self._fences.get(o["name"], 0),
                        int(o.get("term", 0)))
                elif kind == "_req":
                    o = rec["o"]
                    self._req_cache[o["id"]] = (int(o["code"]),
                                                o["resp"])
                    from volcano_tpu.server.durability import REQ_CACHE
                    while len(self._req_cache) > REQ_CACHE:
                        self._req_cache.popitem(last=False)
                elif kind == "_drain":
                    drained = set(rec["o"].get("cids") or [])
                    if drained:
                        self.cluster.commands = [
                            c for c in self.cluster.commands
                            if not (isinstance(c, dict)
                                    and c.get("cid") in drained)]
                else:
                    if erv <= snap_rv:
                        continue    # already in the bootstrap snapshot
                    obj = codec.decode(rec["o"])
                    apply_event_obj(self.cluster, kind, obj)
                    self._track_chips(kind, obj)
                    self._rv = max(self._rv, erv)
                    self._events.append((erv, kind, rec["o"]))
        # durability BEFORE visibility: the fsync advances synced_rv,
        # which is what _visible_rv releases to this replica's readers
        self.durable.commit()
        with self._event_cv:
            self._event_cv.notify_all()

    def on_promote(self) -> None:
        """Follower -> leader: bump the BOOT half of the epoch (same
        BASE: the rv history is WAL-continuous, mirrors delta-resync
        across the promotion) and wake everything gated on roles."""
        new_epoch = self.durable._bump_epoch(continuous=True)
        with self._event_cv:
            self.epoch = new_epoch
            self._event_cv.notify_all()

    @staticmethod
    def _audit_record(idx: int, kind: str, obj) -> dict:
        # vtplint: disable=wall-clock (audit stamps are operator-facing wall time, never deadlines)
        rec = {"i": idx, "ts": time.time(), "kind": kind,
               "key": getattr(obj, "key", None) or
               (obj.get("key") if isinstance(obj, dict) else None)}
        # the two signals the latency exporter needs: pod binding
        # (node set) and job completion (phase terminal)
        node = getattr(obj, "node_name", None)
        if node is not None:
            rec["node"] = node
        phase = getattr(obj, "phase", None)
        if phase is not None:
            rec["phase"] = getattr(phase, "value", str(phase))
        return rec

    def audit_since(self, since: int, limit: int = 10_000,
                    key: str = "") -> Tuple[int, List[dict], bool]:
        """(idx, records with index > since, lost) — no long-poll, the
        exporter pages with `since` until a short batch comes back.
        The first call enables collection.  lost is True when the
        client's position fell off the ring (records were evicted
        unseen) — like events_since's resync signal.  limit bounds the
        copy made under the store lock so a lagging exporter can't
        stall mutations for a 200k-record copy."""
        with self._event_cv:
            self._audit_enabled = True
            if since > self._audit_idx:
                # client ahead of the server: the audit index restarted
                # (the trail is in-memory; a crash resets it) — signal
                # lost so the exporter re-anchors instead of paging
                # into a void forever
                return self._audit_idx, [], True
            if not self._audit:
                return self._audit_idx, [], False
            first = self._audit[0]["i"]
            lost = since < first - 1
            start = max(0, since - first + 1)
            records = list(itertools.islice(
                self._audit, start, start + max(1, limit)))
            idx = records[-1]["i"] if records else self._audit_idx
            if key:
                # server-side object filter (pod describe): paging
                # indices stay ring-global, only matching records ship
                records = [r for r in records if r.get("key") == key]
            return idx, records, lost

    def add_trace(self, doc: dict) -> None:
        from volcano_tpu import trace as trace_mod
        # the never-serve-half-a-tree gate on POST /trace (shared
        # definition: trace.is_complete_span)
        if not isinstance(doc, dict) or \
                not trace_mod.is_complete_span(doc.get("root")):
            raise ValueError("trace rejected: incomplete span tree")
        with self._lock:
            self._traces.append(dict(doc, epoch=self.epoch))

    def traces(self, job: str = "", limit: int = 0,
               episode: str = "") -> List[dict]:
        from volcano_tpu import trace as trace_mod
        with self._lock:
            out = list(self._traces)
        if job:
            out = [t for t in out if trace_mod.matches_job(t, job)]
        if episode:
            out = [t for t in out
                   if trace_mod.matches_episode(t, episode)]
        if limit:
            out = out[-limit:]
        return out

    def events_since(self, since: int, timeout: float = 25.0):
        """(rv, events, resync) — blocks up to timeout for news.

        Only DURABLE events are released (_visible_rv): an event whose
        WAL record is not yet fsync'd stays invisible, so no mirror
        can ever hold state a crash would un-happen.  commit() wakes
        the waiters once the horizon advances."""
        deadline = time.monotonic() + timeout
        with self._event_cv:
            while True:
                if since > self._rv:
                    # the client is AHEAD of us: its revision came
                    # from another incarnation (a restart that did
                    # not keep this history) — tell it to resync NOW
                    # instead of letting the long-poll run out first
                    return self._visible_rv(), [], True
                if self._events and self._events[0][0] > since + 1:
                    # client fell off the ring: it must re-list
                    return self._visible_rv(), [], True
                vis = self._visible_rv()
                if vis > since and self._events:
                    # rvs are contiguous: the suffix starts at a known
                    # offset — never scan the whole (up to 100k) ring
                    start = since - self._events[0][0] + 1
                    news = [e for e in itertools.islice(
                        self._events, max(0, start), None)
                        if e[0] <= vis]
                    if news:
                        return vis, news, False
                remain = deadline - time.monotonic()
                if remain <= 0:
                    return vis, [], False
                self._event_cv.wait(remain)

    def snapshot_payload(self) -> dict:
        """Full store dump + current rv (client list+watch bootstrap).
        The /snapshot route commits BEFORE serving this, so the state
        a mirror bootstraps from is always durable."""
        with self._event_cv:
            rv = self._rv
            stores = {}
            with self.cluster._lock:
                for kind, spec in KINDS.items():
                    store = getattr(self.cluster, spec.attr, {})
                    stores[kind] = {k: codec.encode(v)
                                    for k, v in store.items()}
                stores["_commands"] = codec.encode(
                    list(self.cluster.commands))
        return {"rv": rv, "stores": stores, "epoch": self.epoch}

    # -- leases (leader election) --------------------------------------

    def _wal_lease(self, name: str, holder: str,
                   expires_wall: float, term: int = 0) -> None:
        """Journal a lease transition (holder "" = release).  Wall
        expiry on the wire/disk, rebased to the monotonic clock at
        boot: a restarted server honours the remaining TTL and cannot
        elect a second leader inside an old holder's term.  The term
        rides in the record so a replay/ship never regresses the
        per-name counter."""
        if self.durable is not None:
            # vtplint: disable=append-lock (every caller holds _lock — lease() acquires it around the CAS; the lexical rule cannot see through the call)
            self.durable.append({"k": "_lease", "o": {
                "name": name, "holder": holder,
                "expires_wall": expires_wall, "term": term}})

    def lease(self, name: str, holder: str, ttl: float,
              release: bool = False) -> dict:
        now = time.monotonic()
        with self._lock:
            cur = self._leases.get(name)
            if release:
                if cur and cur.holder == holder:
                    del self._leases[name]
                    self._wal_lease(name, "", 0.0,
                                    self._lease_terms.get(name, 0))
                return {"acquired": False, "holder": "", "expires": 0,
                        "expires_in": 0,
                        "term": self._lease_terms.get(name, 0)}
            if cur is None or cur.expires < now or cur.holder == holder:
                if cur is not None and cur.holder == holder and \
                        cur.expires >= now:
                    # live same-holder renewal: the term is unchanged —
                    # a fencing token names one continuous tenancy
                    term = cur.term or self._lease_terms.get(name, 0)
                else:
                    # fresh acquisition (new holder, or the same holder
                    # returning after an expiry during which another
                    # writer could have been elected): mint a new term
                    term = self._lease_terms.get(name, 0) + 1
                    self._lease_terms[name] = term
                self._leases[name] = Lease(holder, now + ttl, term)
                # vtplint: disable=wall-clock (the wire/journal carry wall expiries by contract; the live deadline above is monotonic)
                self._wal_lease(name, holder, time.time() + ttl, term)
                # vtplint: disable=wall-clock (wire expiry; expires_in is the authoritative TTL)
                return {"acquired": True, "holder": holder,
                        # vtplint: disable=wall-clock (wire expiry by contract)
                        "expires": time.time() + ttl,
                        "expires_in": round(ttl, 3), "term": term}
            # vtplint: disable=wall-clock (wire expiry; expires_in is the authoritative TTL)
            return {"acquired": False, "holder": cur.holder,
                    # vtplint: disable=wall-clock (wire expiry by contract)
                    "expires": time.time() + (cur.expires - now),
                    "expires_in": round(cur.expires - now, 3),
                    "term": cur.term}

    # -- fencing tokens (deposed-writer refusal) -----------------------

    def advance_fence(self, name: str, term: int) -> dict:
        """Raise the fence floor for *name* to *term* (monotonic: a
        lower ask is a no-op, never a regression).  A freshly promoted
        leaseholder advances the fence on every plane it writes to
        BEFORE its first mutation, so the deposed holder's in-flight
        writes are already refusable when they land."""
        term = int(term)
        with self._lock:
            cur = self._fences.get(name, 0)
            if term > cur:
                self._fences[name] = cur = term
                if self.durable is not None:
                    # vtplint: disable=append-lock (held: this branch runs under self._lock)
                    self.durable.append({"k": "_fence", "o": {
                        "name": name, "term": term}})
            return {"name": name, "term": cur,
                    "refused": self._fenced_counts.get(name, 0)}

    def check_fence(self, name: str, term: int) -> None:
        """Refuse a write fenced below the floor (raises ValueError ->
        409).  A HIGHER term self-advances the floor: the first write
        of a new tenancy proves the old one dead even if the explicit
        advance_fence never arrived."""
        term = int(term)
        with self._lock:
            cur = self._fences.get(name, 0)
            if term < cur:
                self._fenced_counts[name] = \
                    self._fenced_counts.get(name, 0) + 1
                count = self._fenced_counts[name]
            elif term > cur:
                self._fences[name] = term
                if self.durable is not None:
                    # vtplint: disable=append-lock (held: this branch runs under self._lock)
                    self.durable.append({"k": "_fence", "o": {
                        "name": name, "term": term}})
                return
            else:
                return
        from volcano_tpu import metrics
        metrics.inc("fenced_writes_total", fence=name)
        log.warning("fenced write refused: %s term %d < floor %d "
                    "(%d refused so far)", name, term, cur, count)
        raise ValueError(
            f"fenced: {name} term {term} is stale (current fence "
            f"{cur}); a newer holder owns this tenancy")

    def fence_status(self) -> dict:
        with self._lock:
            return {name: {"term": t,
                           "refused": self._fenced_counts.get(name, 0)}
                    for name, t in sorted(self._fences.items())}


class _Handler(BaseHTTPRequestHandler):
    server_version = "volcano-tpu-state"
    protocol_version = "HTTP/1.1"
    state: StateServer = None          # injected by serve()
    token: str = ""                    # bearer token, all data routes
    faults = None                      # faults.FaultPlan or None

    # quiet the default stderr access log
    def log_message(self, fmt, *args):  # noqa: N802
        log.debug("http: " + fmt, *args)

    # -- fault injection (volcano_tpu/faults.py, site="server") -------

    def _wire_fault(self, allowed=None):
        """Consult the fault plan once per request.  Pre-response
        kinds are applied HERE (delay/reorder park, 503, reset,
        drop_request); kinds that act at response time (duplicate,
        drop_response, trickle) return the rule for the route methods
        to honour.  allowed narrows to the kinds THIS method can
        express (GET cannot meaningfully duplicate) so a rule's
        injection budget is never burned on a request that can't
        apply it — the fault_injected_total counts stay honest."""
        plan = self.faults
        if plan is None:
            return None
        rule = plan.decide("server", urlparse(self.path).path,
                           kinds=allowed)
        if rule is None:
            return None
        kind = rule.kind
        if kind == "delay":
            time.sleep((rule.ms or 50.0) / 1000.0)
            return None
        if kind == "reorder":
            plan.reorder_park((rule.ms or 150.0) / 1000.0)
            return None
        if kind == "http_503":
            self._json(503, {"error": "injected fault: 503"},
                       headers={"Retry-After": RETRY_AFTER_S})
            return "handled"
        if kind in ("reset", "drop_request"):
            if kind == "drop_request":
                # drain the body first: the request is READ then
                # dropped on the floor (never processed) — distinct
                # from reset, which cuts the connection mid-send
                length = int(self.headers.get("Content-Length") or 0)
                if length:
                    self.rfile.read(length)
            else:
                try:
                    import socket as _socket
                    import struct
                    # RST instead of FIN on close
                    self.connection.setsockopt(
                        _socket.SOL_SOCKET, _socket.SO_LINGER,
                        struct.pack("ii", 1, 0))
                except OSError:
                    # vtplint: disable=except-pass (best-effort RST styling on an injected reset; the close itself still happens)
                    pass
            self.close_connection = True
            return "handled"
        return rule        # drop_response / duplicate / trickle

    def _readonly_503(self, reason: str):
        return self._json(503, {
            "error": f"store is read-only ({reason}); the server "
                     "degrades instead of acking un-durable state",
            "readonly": True},
            headers={"Retry-After": RETRY_AFTER_S})

    def _follower_503(self, unproven: bool = False):
        """A mutation hit a replica whose write path is dead — or a
        READ hit a replica that has not yet re-proven continuity with
        the group (a rebooting deposed leader must not serve its
        possibly-diverged local tail) — the read-only degrade shape
        (503 + Retry-After) with the leader hint the client
        re-routes on."""
        repl = self.state.repl
        what = ("has not re-synced with the group yet; reads come "
                "back after its bootstrap" if unproven else
                "refuses writes; they go to the leader")
        return self._json(503, {
            "error": f"replica {repl.replica_id} "
                     f"({repl.role}, term {repl.term}) {what}",
            "readonly": True, "follower": True,
            "leader": repl.leader_hint()},
            headers={"Retry-After": RETRY_AFTER_S})

    def _authorized(self) -> bool:
        """Every data route — reads included — requires the cluster
        bearer token when one is configured (VERDICT r4 weak #4: an
        open LIST/WATCH hands any peer the whole cluster state).
        Only /healthz (liveness probes can't carry credentials) and
        /metrics (Prometheus scrape; the generated scrape config
        carries the token, but an operator pointing a stock scraper
        at it must not lose telemetry) stay anonymous."""
        from volcano_tpu.server.tlsutil import token_ok
        if token_ok(self.token, self.headers.get("Authorization")):
            return True
        self._json(401, {"error": "missing or invalid bearer token"})
        return False

    def _json(self, code: int, payload, headers=None,
              trickle_ms: float = 0.0) -> None:
        from volcano_tpu.server.httputil import json_response
        json_response(self, code, payload, headers=headers,
                      trickle_ms=trickle_ms)

    def _body(self) -> dict:
        length = int(self.headers.get("Content-Length") or 0)
        if not length:
            return {}
        return json.loads(self.rfile.read(length))

    # -- GET -----------------------------------------------------------

    def do_GET(self):  # noqa: N802
        url = urlparse(self.path)
        st = self.state
        if url.path == "/healthz":
            return self._json(200, {"ok": True})
        if url.path == "/metrics":
            from volcano_tpu import metrics
            return metrics.write_exposition(self)
        fault = self._wire_fault(allowed=(
            "drop_request", "drop_response", "delay", "reorder",
            "http_503", "reset", "trickle"))
        if fault == "handled":
            return None
        if fault is not None and fault.kind == "drop_response":
            # a read has no side effects to commit: its lost response
            # is indistinguishable from a dropped request — cut now
            self.close_connection = True
            return None
        trickle = fault.ms or 20.0 if fault is not None \
            and fault.kind == "trickle" else 0.0
        if not self._authorized():
            return None
        if st.repl is not None and not st.repl.proven and \
                url.path not in ("/replication", "/faults"):
            # an unproven follower (rebooting deposed leader, pending
            # bootstrap) serves NO store reads: its local tail may
            # hold records the group's quorum never acked, which the
            # imminent re-sync will discard — state a mirror must
            # never have seen
            return self._follower_503(unproven=True)
        if url.path == "/snapshot":
            from volcano_tpu.server.durability import ReadOnlyError
            if st.readonly_reason:
                # the full dump would embed events the poisoned WAL
                # never made durable — a mirror bootstrapping from it
                # would hold state a crash un-happens.  Watch/delta
                # reads stay up (they gate on the synced horizon);
                # LISTs wait out the degrade.
                return self._readonly_503(st.readonly_reason)
            payload = st.snapshot_payload()
            # fsync-before-serve: the captured state embeds events up
            # to payload["rv"]; committing them first means no mirror
            # ever bootstraps from state a crash could un-happen
            try:
                st.commit()
            except ReadOnlyError as e:
                return self._readonly_503(e.reason)
            return self._json(200, payload, trickle_ms=trickle)
        if url.path == "/faults":
            # the chaos engine's own observability: which rules have
            # fired how often, and the seed that replays the run
            if self.faults is None:
                return self._json(200, {"active": False})
            return self._json(200, {
                "active": True, "seed": self.faults.seed,
                "rules": self.faults.status()})
        if url.path == "/durability":
            return self._json(200, st.durability_status())
        if url.path == "/replication":
            if st.repl is None:
                return self._json(200, {"enabled": False})
            return self._json(200, dict(st.repl.status(),
                                        enabled=True,
                                        epoch=st.epoch))
        if url.path == "/wal":
            # WAL shipping lane: framed records past the caller's seq,
            # long-polled.  Two classes of tail share the route:
            # replica followers (the request doubles as the follower's
            # durability ack — applied_seq/applied_rv feed the commit
            # quorum) and, with ?mirror=1, federation object mirrors
            # (non-quorum, durable-only; see StateServer.mirror_ship)
            q = parse_qs(url.query)

            def qi(name, default=0):
                try:
                    return int(q.get(name, [default])[0])
                except (TypeError, ValueError):
                    return default
            try:
                timeout = min(float(q.get("timeout", ["5"])[0]), 30.0)
            except (TypeError, ValueError):
                timeout = 5.0
            if q.get("mirror", ["0"])[0] in ("1", "true"):
                if st.durable is None:
                    return self._json(404, {"error": "not durable"})
                resp = st.mirror_ship(since_seq=qi("since_seq"),
                                      timeout=timeout)
            elif st.repl is None:
                return self._json(404, {"error": "not replicated"})
            else:
                resp = st.repl.ship(
                    since_seq=qi("since_seq"),
                    follower=q.get("follower", ["?"])[0],
                    applied_seq=qi("applied_seq"),
                    applied_rv=qi("applied_rv"),
                    term=qi("term"),
                    timeout=timeout)
            if self.faults is not None and resp.get("records"):
                rule = self.faults.decide("server", "/wal",
                                          kinds=("corrupt_ship",))
                if rule is not None:
                    # shipped-segment corruption: flip a byte INSIDE
                    # one framed record (the JSON envelope stays
                    # valid; only the follower's per-record CRC can
                    # tell) — the follower must refuse, never apply
                    recs = list(resp["records"])
                    mid = recs[len(recs) // 2]
                    pos = min(len(mid) - 2, max(12, len(mid) // 2))
                    flipped = chr(ord(mid[pos]) ^ 0x08)
                    recs[len(recs) // 2] = (mid[:pos] + flipped +
                                            mid[pos + 1:])
                    resp = dict(resp, records=recs)
            return self._json(200, resp, trickle_ms=trickle)
        if url.path == "/replica_snapshot":
            # follower bootstrap: the FULL disk doc (stores + leases +
            # req cache) plus seq/term/epoch.  Leader-only — a
            # follower's own horizon could be stale — and refused
            # while read-only, like /snapshot.
            from volcano_tpu.server.durability import ReadOnlyError
            if st.durable is None:
                return self._json(404, {"error": "not durable"})
            if st.repl is not None and not st.repl.is_leader:
                return self._follower_503()
            if st.readonly_reason:
                return self._readonly_503(st.readonly_reason)
            try:
                doc = st.replica_snapshot_doc()
            except ReadOnlyError as e:
                return self._readonly_503(e.reason)
            return self._json(200, doc, trickle_ms=trickle)
        if url.path == "/leases":
            now = time.monotonic()
            with st._lock:
                return self._json(200, {
                    name: {"holder": l.holder,
                           "expires_in": round(l.expires - now, 3),
                           "term": l.term}
                    for name, l in st._leases.items()})
        if url.path == "/fences":
            # fence floors + refused-write counts (vtpctl routers /
            # the chaos conductor's stale-fence invariant read this)
            return self._json(200, st.fence_status())
        if url.path == "/watch":
            # timeout=0 doubles as the DELTA RESYNC lane: the events
            # since a revision, returned immediately — a mirror whose
            # rv is still inside the event ring catches up in O(churn)
            # instead of re-LISTing; resync=true means the revision
            # fell off the compaction horizon (the ring) and only a
            # full /snapshot recovers
            q = parse_qs(url.query)
            since = int(q.get("since", ["0"])[0])
            timeout = min(float(q.get("timeout", ["25"])[0]), 55.0)
            rv, events, resync = st.events_since(since, timeout)
            return self._json(200, {
                "rv": rv, "resync": resync, "epoch": st.epoch,
                "events": [{"rv": r, "kind": k, "obj": o}
                           for r, k, o in events]},
                trickle_ms=trickle)
        if url.path == "/bandwidth":
            # per-node DCN accounting reports (api/netusage.py), the
            # GET-route view of what the agents measured; ?node=
            # narrows to one host
            q = parse_qs(url.query)
            want = q.get("node", [""])[0]
            with st.cluster._lock:
                reports = {
                    name: codec.encode(rep) for name, rep in
                    getattr(st.cluster, "bandwidthreports", {}).items()
                    if not want or name == want}
            return self._json(200, {"reports": reports})
        if url.path == "/traces":
            # recent scheduler session traces (the flight recorder's
            # query surface; vtpctl trace / tools/trace_report.py).
            # ?job= filters to traces touching one job key; the epoch
            # tells a client whether the ring's history predates a
            # server restart
            q = parse_qs(url.query)
            job = q.get("job", [""])[0]
            episode = q.get("episode", [""])[0]
            limit = int(q.get("limit", ["0"])[0])
            return self._json(200, {
                "epoch": st.epoch,
                "traces": st.traces(job=job, limit=limit,
                                    episode=episode)})
        if url.path == "/fleet_trace":
            # the stitched cross-plane span tree for one causal
            # episode (written by the leaseholder router's stitcher
            # into the fleet_trace dict-kind; durable, so a promoted
            # standby serves the same artifact)
            q = parse_qs(url.query)
            episode = q.get("episode", [""])[0]
            if not episode:
                return self._json(400, {"error": "missing episode"})
            with st.cluster._lock:
                doc = getattr(st.cluster, "fleet_traces",
                              {}).get(episode)
            if doc is None:
                return self._json(404, {
                    "error": f"no stitched trace for {episode!r}"})
            return self._json(200, {"episode": episode, "trace": doc})
        if url.path == "/audit":
            q = parse_qs(url.query)
            since = int(q.get("since", ["0"])[0])
            key = q.get("key", [""])[0]
            idx, records, lost = st.audit_since(since, key=key)
            return self._json(200, {"idx": idx, "records": records,
                                    "lost": lost})
        return self._json(404, {"error": f"no route {url.path}"})

    # -- POST ----------------------------------------------------------

    def do_POST(self):  # noqa: N802
        fault = self._wire_fault()
        if fault == "handled":
            return None
        if not self._authorized():
            return None
        url = urlparse(self.path)
        st = self.state
        try:
            body = self._body()
        except (ValueError, json.JSONDecodeError) as e:
            return self._json(400, {"error": str(e)})
        if url.path in ("/campaign", "/promote"):
            # replication control plane: votes and forced promotion
            # bypass the write gates (they are ABOUT the gates)
            if st.repl is None:
                return self._json(404, {"error": "not replicated"})
            if url.path == "/campaign":
                return self._json(200, st.repl.handle_campaign(body))
            if st.repl.is_leader:
                return self._json(200, {"ok": True, "already": True,
                                        "term": st.repl.term})
            # promote() may ABANDON (term moved / vote granted to a
            # concurrent candidate mid-call): report that truthfully
            # — an operator forcing failover must not see a false ok
            won = st.repl.promote(st.repl.term + 1)
            return self._json(200, {"ok": won,
                                    "role": st.repl.role,
                                    "term": st.repl.term})
        # follower gate: a replica whose write path is dead refuses
        # every mutation with the read-only 503 shape + a leader hint
        # (PR 8's degrade mode IS this role, minus the hint)
        if st.repl is not None and not st.repl.may_write():
            return self._follower_503()
        # read-only degrade gate: while the WAL is poisoned nothing
        # can be made durable, so mutation routes are refused UP FRONT
        # (503 + Retry-After) before they touch the in-memory store —
        # memory and disk must not drift apart under a full disk.
        # Leases and traces stay served (READONLY_OK_POSTS).
        if st.readonly_reason and url.path not in READONLY_OK_POSTS:
            return self._readonly_503(st.readonly_reason)
        if fault is not None and fault.kind == "duplicate":
            # the in-network duplicated request: the same body is
            # delivered twice, back to back.  The first delivery runs
            # the full pipeline (its ack is discarded — the network
            # "kept" the duplicate); the answer below comes from the
            # second.  Idempotency keys make the pair collapse to one
            # application; unkeyed mutations must be state-compare
            # safe — exactly what this fault exists to prove.
            import copy
            self._process_post(url.path, copy.deepcopy(body), st)
        code, payload, _req_id = self._process_post(url.path, body, st)
        # durability barrier BEFORE the ack: every event this request
        # caused (and its idempotency record) is fsync'd in the WAL —
        # the journals-before-acking contract the reference gets from
        # etcd
        from volcano_tpu.server.durability import ReadOnlyError
        try:
            st.commit()
        except ReadOnlyError as e:
            if url.path in READONLY_OK_POSTS:
                # leases/traces keep serving from memory through the
                # degrade: their journal records are dropped (state
                # re-captured wholesale at heal), and leader election
                # must not stall on a full disk
                pass
            else:
                # the mutation applied in memory but cannot be made
                # durable YET: 503, never ack.  The recorded
                # idempotency verdict is deliberately KEPT — it and
                # the in-memory state share fate exactly: heal()'s
                # full snapshot persists both together (the retry
                # then replays the verdict for state that IS
                # durable), while a crash before heal loses both
                # together (the retry re-applies for real).
                # Forgetting the verdict here would double-apply
                # non-idempotent mutations after a heal: the command
                # the 503'd attempt left in memory becomes durable,
                # and the retry — finding no recorded verdict —
                # appends a second one.
                return self._readonly_503(e.reason)
        if fault is not None and fault.kind == "drop_response":
            # the ack-lost case: committed, durable, and the client
            # will never know — its retry (idempotency key or
            # state-compare) must converge, not double-apply
            self.close_connection = True
            return None
        trickle = fault.ms or 20.0 if fault is not None \
            and fault.kind == "trickle" else 0.0
        return self._json(code, payload, trickle_ms=trickle)

    def _process_post(self, path: str, body, st) -> tuple:
        """Route one POST body: idempotency replay, dispatch, verdict
        recording.  Returns (code, payload, req_id) — commit/ack is
        the caller's job."""
        # idempotency key: a retried mutation whose first attempt
        # committed (crash/partition between commit and ack) must get
        # the recorded verdict back, never double-apply — the replay-
        # safe half of the client's retry policy.  The cache itself is
        # journaled (_req WAL records + snapshots), so it survives the
        # very crash it exists for.
        req_id = body.pop("_req_id", None) if isinstance(body, dict) \
            else None
        # fence gate, BEFORE the idempotency replay: a deposed
        # holder's retry must get the 409 even where its first attempt
        # committed and recorded a verdict — the refusal is about WHO
        # is writing now, not what the write would do
        fence = body.pop("_fence", None) if isinstance(body, dict) \
            else None
        if isinstance(fence, dict) and fence.get("name"):
            try:
                st.check_fence(fence["name"],
                               int(fence.get("term", 0)))
            except ValueError as e:
                return 409, {"error": str(e)}, None
        if req_id:
            hit = st.replay_response(req_id)
            if hit is not None:
                return hit[0], hit[1], None
        try:
            code, payload = self._route_post(path, body, st)
        except KeyError as e:
            code, payload = 404, {"error": str(e)}
        except ValueError as e:
            # discriminate by TYPE, never message wording (see
            # _error_code): admission veto 422, conflict 409
            code, payload = _error_code(e), {"error": str(e)}
        except Exception as e:  # noqa: BLE001 — surface, don't kill thread
            log.exception("POST %s failed", path)
            code, payload = 500, {"error": str(e)}
        if req_id and code < 500:
            # 4xx verdicts are deterministic state-compare outcomes:
            # recording them keeps a retry's answer stable; 5xx is a
            # server fault the retry should re-attempt for real
            st.record_response(req_id, code, payload)
        return code, payload, req_id

    def _route_post(self, path: str, body: dict, st) -> tuple:
        cl = st.cluster
        if path.startswith("/objects/"):
            kind = path[len("/objects/"):]
            if kind not in KINDS:
                return 404, {"error": f"unknown kind {kind}"}
            obj = codec.decode(body["obj"])
            key = body.get("key")
            if kind == "pod":
                # whole-pod writes go through the same chip-guard as
                # /bind: check-and-put is atomic under _bind_mutex so
                # a concurrent bind cannot slip between them
                with st._bind_mutex:
                    err = st.check_put_capacity(obj)
                    if err:
                        raise ValueError(err)       # -> 409
                    stored = cl.put_object(kind, obj, key=key)
            else:
                stored = cl.put_object(kind, obj, key=key)
            return 200, {"obj": codec.encode(stored)}
        if path == "/bind":
            with st._bind_mutex:
                err = st.check_bind_capacity(
                    body["namespace"], body["name"], body["node_name"])
                if err:
                    raise ValueError(err)       # -> 409
                cl.bind_pod(body["namespace"], body["name"],
                            body["node_name"],
                            ts_alloc=body.get("ts_alloc"))
            return 200, {"ok": True}
        if path == "/bind_batch":
            # a gang's binds as ONE request (the wire fast lane's
            # biggest round-trip saving: 256 POSTs -> 1).  Failure
            # stays per-item — same verdict the per-pod route
            # would have returned, so a conflict on one pod never
            # vetoes its gang-mates.  Per-item state-compare keeps a
            # whole-batch retry replay-safe: a pod the first attempt
            # already bound re-verdicts as success (same node), not
            # 409.
            results = []
            bound = 0
            with st._bind_mutex:
                for b in body.get("binds", []):
                    try:
                        if b.get("pod") is not None and \
                                f"{b['namespace']}/{b['name']}" \
                                not in cl.pods:
                            # keyspace-partitioned write plane: the
                            # pending pod lived in the META leader
                            # group; its bind relocates it here, to the
                            # group owning the node, so this group's
                            # chip accounting sees node AND occupant
                            # together.  Admit-then-bind is one atomic
                            # step under _bind_mutex; the admitted pod
                            # is nodeless/Pending, so the put guard has
                            # nothing to refuse and the capacity
                            # verdict below is the only arbiter.
                            cl.put_object("pod", codec.decode(b["pod"]))
                        err = st.check_bind_capacity(
                            b["namespace"], b["name"], b["node_name"])
                        if err:
                            raise ValueError(err)   # -> 409 per-item
                        cl.bind_pod(b["namespace"], b["name"],
                                    b["node_name"],
                                    ts_alloc=b.get("ts_alloc"))
                        results.append({"ok": True})
                        bound += 1
                    except Exception as e:  # noqa: BLE001 — per-item
                        results.append({
                            "ok": False, "code": _error_code(e),
                            "error": str(e) or type(e).__name__})
            return 200, {"bound": bound, "results": results}
        if path == "/evict":
            cl.evict_pod(body["namespace"], body["name"],
                         body.get("reason", ""))
            return 200, {"ok": True}
        if path == "/nominate":
            cl.nominate_pod(body["namespace"], body["name"],
                            body["node_name"])
            return 200, {"ok": True}
        if path == "/podgroup_status":
            cl.update_podgroup_status(codec.decode(body["obj"]))
            return 200, {"ok": True}
        if path == "/record_event":
            cl.record_event(body["obj_key"], body["reason"],
                            body.get("message", ""))
            return 200, {"ok": True}
        if path == "/command":
            cl.add_command(body["target"], body["action"])
            return 200, {"ok": True}
        if path == "/drain_commands":
            cmds = cl.drain_commands(body["target"])
            if cmds and st.durable is not None:
                # drains don't flow through the event log (commands
                # are consumed, not updated) — journal them directly
                # or a replayed WAL would resurrect consumed commands.
                # Journaled by cid: a concurrent add_command's event
                # record can land on either side of this one in the
                # file, so replay removes the exact consumed set
                # regardless of record order
                # vtplint: disable=append-lock (journaled by cid: replay removes the exact consumed set regardless of record order — see the comment above)
                st.durable.append({"k": "_drain", "o": {
                    "target": body["target"],
                    "cids": [c.get("cid") for c in cmds
                             if isinstance(c, dict) and c.get("cid")]}})
            return 200, {"commands": cmds}
        if path == "/trace":
            st.add_trace(body.get("trace"))
            return 200, {"ok": True}
        if path == "/lease":
            return 200, st.lease(
                body["name"], body["holder"],
                float(body.get("ttl", 15.0)),
                release=bool(body.get("release")))
        if path == "/fence":
            return 200, st.advance_fence(
                body["name"], int(body.get("term", 0)))
        if path == "/tick":
            cl.tick()
            return 200, {"ok": True}
        if path == "/complete_pod":
            cl.complete_pod(body["key"],
                            succeeded=bool(body.get("succeeded", True)),
                            exit_code=body.get("exit_code"))
            return 200, {"ok": True}
        return 404, {"error": f"no route {path}"}

    # -- DELETE --------------------------------------------------------

    def do_DELETE(self):  # noqa: N802
        fault = self._wire_fault(allowed=(
            "drop_request", "drop_response", "delay", "reorder",
            "http_503", "reset"))
        if fault == "handled":
            return None
        if not self._authorized():
            return None
        url = urlparse(self.path)
        if self.state.repl is not None and \
                not self.state.repl.may_write():
            return self._follower_503()
        if self.state.readonly_reason:
            return self._readonly_503(self.state.readonly_reason)
        if not url.path.startswith("/objects/"):
            return self._json(404, {"error": f"no route {url.path}"})
        kind = url.path[len("/objects/"):]
        if kind not in KINDS:
            return self._json(404, {"error": f"unknown kind {kind}"})
        q = parse_qs(url.query)
        key = q.get("key", [""])[0]
        if not key:
            return self._json(400, {"error": "missing key"})
        # fence gate (query params — DELETE carries no body): same
        # deposed-writer refusal as the POST path
        fname = q.get("fence_name", [""])[0]
        if fname:
            try:
                fterm = int(q.get("fence_term", ["0"])[0])
            except (TypeError, ValueError):
                fterm = 0
            try:
                self.state.check_fence(fname, fterm)
            except ValueError as e:
                return self._json(409, {"error": str(e)})
        self.state.cluster.delete_object(kind, key)
        from volcano_tpu.server.durability import ReadOnlyError
        try:
            self.state.commit()
        except ReadOnlyError as e:
            return self._readonly_503(e.reason)
        if fault is not None and fault.kind == "drop_response":
            # the ack-lost delete: committed, never told — a retried
            # DELETE of a gone key is a no-op, so it must converge
            self.close_connection = True
            return None
        return self._json(200, {"ok": True})


def serve(port: int = 0, cluster: Optional[FakeCluster] = None,
          tick_period: float = 0.0, tls_cert: str = "",
          tls_key: str = "", token: str = "", data_dir: str = "",
          durable=None, faults=None, wal_force_truncate: bool = False,
          replication=None
          ) -> Tuple[ThreadingHTTPServer, StateServer]:
    """Start the server on 127.0.0.1:port (0 = ephemeral); returns
    (http_server, state).  Caller runs http_server.serve_forever()
    or uses the background thread started here.  tls_cert/tls_key
    make the listener TLS-only; token guards every route except
    /healthz and /metrics.  data_dir (or a pre-built DurableStore via
    durable=) turns on the WAL + snapshot crash-safety layer: every
    mutation is journaled and fsync'd before its ack, and boot replays
    snapshot-then-WAL.  faults (a faults.FaultPlan) arms the chaos
    engine: per-route wire faults at this handler, disk faults on the
    WAL via a FaultyVFS, clock skew installed by the caller.
    wal_force_truncate is the explicit operator override for mid-WAL
    corruption (otherwise boot refuses with WALCorruptionError)."""
    from volcano_tpu import faults as faults_mod
    from volcano_tpu.server.httputil import serve_threaded
    if durable is None and data_dir:
        from volcano_tpu.server.durability import DurableStore
        vfs = None
        if faults is not None and any(r.site == "disk"
                                      for r in faults.rules):
            vfs = faults_mod.FaultyVFS(faults)
        durable = DurableStore(data_dir, vfs=vfs,
                               force_truncate=wal_force_truncate)
    state = StateServer(cluster, durable=durable,
                        replication=replication)
    httpd = serve_threaded(_Handler, {"state": state, "token": token,
                                      "faults": faults},
                           port, "state-server",
                           tls_cert=tls_cert, tls_key=tls_key)
    if replication is not None:
        # the listener is up: peers can reach us, the tail/watchdog
        # threads may start
        if not replication.self_url:
            replication.self_url = \
                f"http://127.0.0.1:{httpd.server_address[1]}"
        replication.start()
    state.tick_stop = threading.Event()
    if tick_period > 0:
        def tick_loop():
            while not state.tick_stop.wait(tick_period):
                try:
                    if state.repl is not None and \
                            not state.repl.may_write():
                        # a follower's kubelet is the LEADER's tick,
                        # shipped like any other mutation
                        continue
                    if state.readonly_reason:
                        # no kubelet mutations while read-only: their
                        # journal records would be dropped, and memory
                        # must not drift from what heal() can capture
                        # consistently
                        continue
                    state.cluster.tick()
                    # tick mutations have no ack path; commit here so
                    # they become watch-visible (and durable) promptly
                    state.commit()
                except Exception as e:  # noqa: BLE001
                    from volcano_tpu.server.durability import \
                        ReadOnlyError
                    if isinstance(e, ReadOnlyError):
                        # quorum not assembled yet / degrade window:
                        # routine for a replicated boot, not an error
                        log.debug("tick commit deferred: %s", e)
                    else:
                        log.exception("tick failed")
        threading.Thread(target=tick_loop, name="kubelet-tick",
                         daemon=True).start()
    if durable is not None:
        def compact_loop():
            while not state.tick_stop.wait(0.5):
                try:
                    durable.status()    # refreshes the WAL gauges
                    if durable.poisoned:
                        # read-only degrade: keep probing for heal —
                        # Retry-After tells clients to check back on
                        # roughly this cadence
                        state.try_heal()
                    elif durable.should_snapshot():
                        state.write_snapshot()
                except Exception:  # noqa: BLE001
                    log.exception("snapshot compaction failed")
        threading.Thread(target=compact_loop, name="wal-compactor",
                         daemon=True).start()
    return httpd, state
