"""TLS + bearer-token plumbing for the wire boundary.

The reference webhook manager and apiserver speak TLS with cert
plumbing (cmd/webhook-manager/, pkg/webhooks/config/); this module is
the rebuild's equivalent for the state server, webhook manager, and
every client (scheduler, controllers, vtpctl): self-signed cert
generation for dev/test clusters, ssl.SSLContext construction for both
sides, and constant-time bearer-token comparison for mutating routes.

One shared cluster token authenticates every component to every other
(the join-token model); cert verification pins the server identity.
"""

from __future__ import annotations

import datetime
import hmac
import ipaddress
import os
import ssl
from typing import Optional, Tuple


def generate_self_signed(cert_path: str, key_path: str,
                         hosts: Tuple[str, ...] = ("127.0.0.1",
                                                   "localhost"),
                         days: int = 365) -> None:
    """Write a self-signed server certificate + key (PEM).  The same
    cert file doubles as the clients' CA bundle (self-signed ==
    self-CA), mirroring the reference's gen-admission-secret flow.

    Uses the `cryptography` package when importable, else falls back
    to the system `openssl` binary (deploy images bake the ML stack,
    not pyca/cryptography — the cert material is identical)."""
    try:
        from cryptography import x509  # noqa: F401
    except ImportError:
        _generate_self_signed_openssl(cert_path, key_path, hosts, days)
        return
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME,
                                         "volcano-tpu")])
    alt_names = []
    for h in hosts:
        try:
            alt_names.append(x509.IPAddress(ipaddress.ip_address(h)))
        except ValueError:
            alt_names.append(x509.DNSName(h))
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=days))
            .add_extension(x509.SubjectAlternativeName(alt_names),
                           critical=False)
            .add_extension(x509.BasicConstraints(ca=True,
                                                 path_length=None),
                           critical=True)
            .sign(key, hashes.SHA256()))
    key_pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption())
    # key first, restrictive mode
    fd = os.open(key_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "wb") as f:
        f.write(key_pem)
    with open(cert_path, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))


def _generate_self_signed_openssl(cert_path: str, key_path: str,
                                  hosts: Tuple[str, ...],
                                  days: int) -> None:
    """`openssl req -x509` fallback producing the same PEM pair (SANs
    for every host, CA:TRUE so the cert self-anchors as the clients'
    bundle).  Key lands first with a restrictive mode, like the
    library path."""
    import shutil
    import subprocess
    openssl = shutil.which("openssl")
    if openssl is None:
        raise RuntimeError(
            "cannot generate a self-signed cert: neither the "
            "`cryptography` package nor an `openssl` binary is "
            "available")
    alt = []
    for h in hosts:
        try:
            ipaddress.ip_address(h)
            alt.append(f"IP:{h}")
        except ValueError:
            alt.append(f"DNS:{h}")
    # pre-create the key with a restrictive mode so openssl's write
    # lands on 0600 (openssl honors existing modes on POSIX)
    fd = os.open(key_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    os.close(fd)
    # NOTE: no explicit basicConstraints — `req -x509` already emits
    # CA:TRUE, and a duplicated extension makes OpenSSL-backed clients
    # reject the chain with `unknown ca`
    cmd = [openssl, "req", "-x509", "-newkey", "rsa:2048", "-nodes",
           "-keyout", key_path, "-out", cert_path,
           "-days", str(days), "-subj", "/CN=volcano-tpu",
           "-addext", f"subjectAltName={','.join(alt)}"]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"openssl cert generation failed: {proc.stderr[-500:]}")
    os.chmod(key_path, 0o600)


def server_ssl_context(cert_path: str, key_path: str) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_path, key_path)
    return ctx


def client_ssl_context(ca_cert: str = "",
                       insecure: bool = False
                       ) -> Optional[ssl.SSLContext]:
    """Context for https:// clients: verify against ca_cert when
    given; insecure=True skips verification (encrypted, unpinned —
    kubectl's insecure-skip-tls-verify).  None for plain http."""
    if insecure:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        return ctx
    if ca_cert:
        return ssl.create_default_context(cafile=ca_cert)
    return None


def token_ok(configured: str, authorization_header: str) -> bool:
    """Constant-time check of 'Authorization: Bearer <token>'.  An
    empty configured token disables auth (dev mode)."""
    if not configured:
        return True
    return hmac.compare_digest(authorization_header or "",
                               f"Bearer {configured}")


def load_token(token: str = "", token_file: str = "") -> str:
    if token:
        return token
    if token_file:
        with open(token_file, encoding="utf-8") as f:
            return f.read().strip()
    return ""
