"""Crash-safe persistence for the state server: WAL + snapshots.

The authoritative store used to be an in-memory FakeCluster whose only
durability was a pickle written on graceful shutdown — a SIGKILL/OOM
lost every acked bind, podgroup phase, quarantine TTL and lease, and
restarted the event log so every mirror's delta resync silently
desynced.  The reference keeps all truth behind an apiserver/etcd that
journals before acking; this module gives volcano-tpu the same
contract (docs/design/durability.md):

  * every store mutation appends ONE record to a write-ahead log and
    is fsync'd before the HTTP ack (group commit: concurrent handler
    threads share one fsync barrier, so a 256-bind burst pays ~1
    fsync, not 256);
  * a periodic snapshot (write-temp + atomic rename + dir fsync)
    compacts the log: snapshot = full store dump + last rv + epoch;
    WAL segments wholly covered by a durable snapshot are deleted;
  * boot replays snapshot-then-WAL-tail, resumes the rv counter
    monotonically, reseeds the watch event ring from the tail, and
    bumps the boot half of the epoch ("BASE.BOOT") so mirrors KNOW a
    restart happened — same BASE means the history is WAL-continuous
    and a delta resync across the restart is exact; a different BASE
    (fresh dir, legacy pickle boot) forces a full re-list.

Record format — one line per record, self-delimiting and
self-verifying:

    crc32hex {"q": seq, "rv": N, "k": kind, "o": <codec payload>}

The 8-hex-char CRC32 covers the JSON body; ``q`` is a per-store
monotonic sequence number.  Together they close the two gray-failure
holes a bare JSON-lines journal has: a bit-flipped record that still
PARSES as JSON (replayed silently before; now a CRC mismatch), and a
duplicated or gapped record stream after an operator copy-restore
(now detected by ``q``).  Replay policy (docs/design/chaos.md):

  * torn FINAL record of the FINAL segment — a crash mid-append —
    is dropped quietly, as before;
  * corruption anywhere else (CRC mismatch, unparseable line,
    sequence gap) REFUSES TO BOOT with ``WALCorruptionError`` — a
    silent partial replay is how acked state quietly vanishes; the
    operator accepts the loss explicitly with ``--wal-force-truncate``
    which cuts the log at the corrupt record and discards the rest;
  * duplicated records (``q`` already applied) are skipped idempotently
    — a copy-restored segment replays to the same state.

Record kinds besides store events (only those carry rv — they are the
watch stream; private records replay in file order):

    {"k": "_lease", "o": {name, holder, expires_wall, term}} lease CAS
    {"k": "_fence", "o": {"name":.., "term":..}}       fence floor raise
    {"k": "_drain", "o": {"target": key}}              command drain
    {"k": "_req",  "o": {"id":..,"code":..,"resp":..}} idempotency key
    {"k": "_probe"}                                    heal probe

Leases persist wall-clock expiry and are rebased onto the monotonic
clock at boot, so a restarted server refuses a second leader inside an
old holder's TTL while a wall-clock jump can never mass-expire (or
immortalize) live leases.

Gray-failure degrade (the fsyncgate lesson): an ENOSPC on append or
an EIO from fsync POISONS the store for writes — fsync is never
retried (a failed fsync may clear the kernel's dirty-page error bit,
so a retry can falsely succeed over lost data).  The server degrades
to READ-ONLY (writes 503 + Retry-After, reads and leases still
served) instead of acking un-durable state, and heals by rotating to
a fresh segment, probing it with a real fsync, and writing a full
snapshot that recaptures the in-memory state wholesale — rv stays
monotonic across the whole episode.  File ops route through a
``faults.VFS`` seam so the chaos engine can inject exactly these
failures deterministically.
"""

from __future__ import annotations

import io
import json
import logging
import os
import threading
import time
import uuid
import zlib
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

log = logging.getLogger(__name__)

SNAPSHOT_FILE = "snapshot.json"
EPOCH_FILE = "epoch.json"
WAL_PREFIX = "wal-"
SNAPSHOT_FORMAT = "volcano-tpu-snapshot-v1"
# compaction thresholds: snapshot once the live WAL holds this many
# records or bytes (whichever first) — bounds both replay time and
# disk growth without paying a full store dump per mutation
SNAPSHOT_EVERY_RECORDS = 20_000
SNAPSHOT_EVERY_BYTES = 64 * 1024 * 1024
# replayed idempotency keys retained (snapshot + memory): a retried
# mutation whose first attempt committed before a crash must find its
# recorded response, not double-apply
REQ_CACHE = 2048
# fsync'd records kept in memory for WAL shipping (server/replication
# .py): a follower inside the ring tails in O(new records); one that
# fell off (or a fresh boot — the ring is volatile) bootstraps from
# the replica snapshot instead
SHIP_RING = 50_000


class WALCorruptionError(RuntimeError):
    """Mid-WAL corruption found at boot: the log cannot be replayed
    without silently dropping acked state.  Refuse to start; the
    operator accepts the loss explicitly with --wal-force-truncate."""

    def __init__(self, path: str, lineno: int, reason: str):
        super().__init__(
            f"WAL {path} corrupt at record {lineno} ({reason}); "
            "refusing to boot — a partial replay would silently drop "
            "every later acked write.  Restore the segment from a "
            "copy, or re-run with --wal-force-truncate to cut the log "
            "here and accept the data loss.")
        self.path = path
        self.lineno = lineno
        self.reason = reason


class ReadOnlyError(RuntimeError):
    """The store is poisoned for writes (failed fsync / full disk):
    nothing can be made durable, so nothing may be acked."""

    def __init__(self, reason: str):
        super().__init__(f"store is read-only: {reason}")
        self.reason = reason


class Recovery(NamedTuple):
    cluster: Optional[object]      # FakeCluster, or None (nothing on disk)
    rv: int                        # resume point for the event counter
    events: List[Tuple[int, str, object]]   # ring tail [(rv, kind, payload)]
    leases: Dict[str, Tuple[str, float]]    # name -> (holder, expires_wall)
    req_cache: "Dict[str, Tuple[int, object]]"  # req id -> (code, payload)
    epoch: str                     # bumped incarnation id "BASE.BOOT"
    replay_records: int
    replay_seconds: float
    # per-name MONOTONIC lease term counters (fencing tokens): survive
    # lease expiry/release — a term, once issued, is never reissued,
    # even across a reboot (or a deposed holder could fence as current)
    lease_terms: Dict[str, int] = {}
    # per-name fence floors: the highest term whose writes were ever
    # accepted — a recovering plane must keep refusing staler terms
    fences: Dict[str, int] = {}


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:            # platform without dir fds
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write_json(path: str, doc: dict) -> None:
    """write-temp + fsync + atomic rename + dir fsync — the one
    snapshot writer every save path routes through (including the
    legacy --state graceful save), so a crash mid-save can never
    leave a torn file where the last good state was."""
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, separators=(",", ":"))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(os.path.abspath(path)) or ".")


def frame_record(rec: dict, seq: int) -> str:
    """One WAL line: crc32hex SP json-body NL.  The CRC covers the
    body bytes; the body carries the sequence number."""
    body = json.dumps(dict(rec, q=seq), separators=(",", ":"))
    return f"{zlib.crc32(body.encode('utf-8')) & 0xffffffff:08x} {body}\n"


def parse_record(line: str) -> Tuple[Optional[dict], str]:
    """(record, "") on success, (None, reason) on a bad line.
    Legacy lines (bare JSON, pre-CRC vintage) still load — they just
    can't prove their own integrity."""
    line = line.strip()
    if not line:
        return None, "blank"
    if line.startswith("{"):
        try:
            return json.loads(line), ""
        except ValueError:
            return None, "unparseable"
    crc_hex, _, body = line.partition(" ")
    if len(crc_hex) != 8 or not body:
        return None, "unframed"
    try:
        want = int(crc_hex, 16)
    except ValueError:
        return None, "unframed"
    if zlib.crc32(body.encode("utf-8")) & 0xffffffff != want:
        return None, "crc-mismatch"
    try:
        return json.loads(body), ""
    except ValueError:
        return None, "unparseable"


def decode_stores_into(cluster, stores: dict) -> None:
    """Fold an encoded snapshot `stores` dict (the /snapshot payload
    shape) into a FakeCluster's attribute stores."""
    from volcano_tpu.api import codec
    from volcano_tpu.cache.kinds import KINDS
    for kind, spec in KINDS.items():
        store = {k: codec.decode(enc)
                 for k, enc in stores.get(kind, {}).items()}
        if store or not getattr(cluster, spec.attr, None):
            # merge over construction defaults (e.g. the default
            # queue) only when the snapshot actually carried the kind
            getattr(cluster, spec.attr).update(store)
    cmds = codec.decode(stores.get("_commands", [])) or []
    cluster.commands = list(cmds)


def apply_event(cluster, kind: str, payload) -> None:
    """Replay ONE WAL store event onto the authoritative store —
    the server-side twin of RemoteCluster._apply_batch: no admission
    (it already ran before the event was logged), no watchers (none
    are attached at boot)."""
    from volcano_tpu.api import codec
    apply_event_obj(cluster, kind, codec.decode(payload))


def apply_event_obj(cluster, kind: str, obj) -> None:
    """apply_event with the payload already decoded — the follower
    apply path decodes once and shares the object with its own
    bookkeeping (chip maps, watch ring)."""
    from volcano_tpu.cache.kinds import KINDS
    deleted = kind.endswith("_deleted")
    base = kind[:-len("_deleted")] if deleted else kind
    spec = KINDS.get(base)
    if spec is not None:
        key = obj["key"] if spec.key_of is None else spec.key_of(obj)
        store = getattr(cluster, spec.attr)
        if deleted:
            store.pop(key, None)
        else:
            store[key] = obj if spec.key_of else obj["obj"]
    elif base == "command":
        cluster.commands.append(obj)
    # unknown kinds (a future version's events) replay as no-ops: the
    # snapshot that follows them will carry whatever they meant


def load_cluster_file(path: str):
    """Load a cluster state file in EITHER format: the legacy pickle
    or the snapshot JSON the graceful save now writes (--state stays
    working as an alias across the format change).  Returns a
    FakeCluster with no admission chain attached."""
    import pickle
    with open(path, "rb") as f:
        head = f.read(1)
        f.seek(0)
        if head != b"{":
            return pickle.load(f)
        doc = json.load(io.TextIOWrapper(f, encoding="utf-8"))
    from volcano_tpu.cache.fake_cluster import FakeCluster
    cluster = FakeCluster()
    decode_stores_into(cluster, doc.get("stores", {}))
    return cluster


class DurableStore:
    """Owns the WAL segments + snapshot of one state-server data dir."""

    def __init__(self, data_dir: str,
                 snapshot_every_records: int = SNAPSHOT_EVERY_RECORDS,
                 snapshot_every_bytes: int = SNAPSHOT_EVERY_BYTES,
                 vfs=None, force_truncate: bool = False):
        from volcano_tpu import faults
        self.dir = os.path.abspath(data_dir)
        os.makedirs(self.dir, exist_ok=True)
        self.snapshot_every_records = snapshot_every_records
        self.snapshot_every_bytes = snapshot_every_bytes
        self.vfs = vfs if vfs is not None else faults.VFS()
        self.force_truncate = force_truncate
        self._lock = threading.Lock()     # file handle + counters
        # serializes whole snapshot() sequences: the background
        # compactor and the graceful-save path must never interleave
        # rotate/capture/rename/delete (a slower older capture could
        # overwrite a newer snapshot AFTER the newer call deleted the
        # WAL segments covering the difference)
        self._snap_lock = threading.Lock()
        self._file: Optional[io.TextIOBase] = None
        self._seg_seq = 0
        self._seq = 0                     # last record sequence written
        self._appended = 0                # records since last fsync mark
        self._synced_marker = 0
        self._tail_rv = 0                 # last store-event rv appended
        self.synced_rv = 0                # last store-event rv fsync'd
        self.synced_seq = 0               # last record seq fsync'd
        # shipping ring: (seq, line) of recent records, served to
        # follower replicas up to the fsync horizon (ship_since)
        import collections
        self._ship: "collections.deque" = collections.deque(
            maxlen=SHIP_RING)
        self.wal_records = 0              # records in live segments
        self.wal_bytes = 0
        self.snapshot_rv = 0
        self.snapshot_at = 0.0            # wall time of last snapshot
        self.last_fsync_s = 0.0
        self.replay_records = 0
        self.replay_seconds = 0.0
        self.recovery: Optional[Recovery] = None
        # read-only poison: set by the FIRST append/fsync failure,
        # cleared only by a successful heal().  While set, appends are
        # dropped (counted) and commit() raises ReadOnlyError — the
        # server above must 503 writes instead of acking them.
        self.poisoned = ""

    # -- boot ----------------------------------------------------------

    def _segments(self) -> List[str]:
        try:
            names = sorted(n for n in os.listdir(self.dir)
                           if n.startswith(WAL_PREFIX)
                           and n.endswith(".log"))
        except FileNotFoundError:
            return []
        return [os.path.join(self.dir, n) for n in names]

    def _bump_epoch(self, continuous: bool) -> str:
        """Read/advance the incarnation id.  BASE survives as long as
        the WAL history is continuous (mirrors may delta-resync across
        the restart); a dir with no durable state mints a fresh BASE
        (mirrors must full re-list — their rv space is meaningless
        here)."""
        path = os.path.join(self.dir, EPOCH_FILE)
        base, boot = "", 0
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
            base, boot = doc.get("base", ""), int(doc.get("boot", 0))
        except (OSError, ValueError):
            # vtplint: disable=except-pass (first boot or corrupt epoch doc: fall through to a fresh base below)
            pass
        if not base or not continuous:
            base = uuid.uuid4().hex[:12]
        boot += 1
        atomic_write_json(path, {"base": base, "boot": boot})
        return f"{base}.{boot}"

    @staticmethod
    def _scan_segment(path: str) -> List[Tuple[int, int, bytes, bool]]:
        """[(lineno, byte_offset, raw_line, ended_with_newline)] —
        offsets let force-truncate cut the file at the exact corrupt
        record."""
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            log.exception("WAL segment %s unreadable", path)
            return []
        out = []
        off = 0
        lineno = 0
        for chunk in raw.split(b"\n"):
            complete = off + len(chunk) < len(raw)   # had its newline
            if chunk.strip():
                lineno += 1
                out.append((lineno, off, chunk, complete))
            off += len(chunk) + 1
        return out

    def _handle_corruption(self, path: str, lineno: int, offset: int,
                           reason: str) -> None:
        """Mid-WAL corruption: refuse to boot, or — with the explicit
        operator override — truncate the log at the corrupt record
        (and drop every later segment) so the surviving prefix is the
        whole story."""
        from volcano_tpu import metrics
        if not self.force_truncate:
            raise WALCorruptionError(path, lineno, reason)
        dropped_bytes = os.path.getsize(path) - offset
        with open(path, "r+b") as f:
            f.truncate(offset)
        later = [s for s in self._segments() if s > path]
        for seg in later:
            try:
                os.remove(seg)
            except OSError:
                log.warning("could not remove post-corruption WAL %s",
                            seg)
        metrics.inc("server_wal_dropped_records_total",
                    reason="force-truncate")
        log.error("WAL %s corrupt at record %d (%s): "
                  "--wal-force-truncate cut %d bytes here and dropped "
                  "%d later segment(s) — ACKED STATE MAY BE LOST",
                  path, lineno, reason, dropped_bytes, len(later))

    def recover(self, event_ring: int = 100_000) -> Recovery:
        """Snapshot + WAL-tail replay; opens a fresh live segment.
        Returns cluster=None when the dir held no durable state (the
        caller seeds it and writes the initial snapshot).

        Raises WALCorruptionError on mid-WAL corruption (CRC mismatch,
        unparseable record, sequence gap) unless force_truncate was
        set; only a torn final record of the final segment — the
        crash-mid-append shape — is dropped quietly."""
        from volcano_tpu import metrics
        from volcano_tpu.cache.fake_cluster import FakeCluster

        t0 = time.perf_counter()
        snap_path = os.path.join(self.dir, SNAPSHOT_FILE)
        doc = None
        if os.path.exists(snap_path):
            try:
                with open(snap_path, encoding="utf-8") as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                log.exception("snapshot %s unreadable; replaying WAL "
                              "from scratch", snap_path)
        segments = self._segments()
        had_state = doc is not None or bool(segments)

        cluster = None
        rv = 0
        last_seq = 0
        leases: Dict[str, Tuple[str, float]] = {}
        req_cache: Dict[str, Tuple[int, object]] = {}
        lease_terms: Dict[str, int] = {}
        fences: Dict[str, int] = {}
        if doc is not None:
            cluster = FakeCluster()
            decode_stores_into(cluster, doc.get("stores", {}))
            rv = int(doc.get("rv", 0))
            last_seq = int(doc.get("wal_seq", 0))
            for name, rec in (doc.get("leases") or {}).items():
                leases[name] = (rec["holder"], float(rec["expires_wall"]))
                if rec.get("term"):
                    lease_terms[name] = int(rec["term"])
            for name, t in (doc.get("lease_terms") or {}).items():
                lease_terms[name] = max(lease_terms.get(name, 0), int(t))
            for name, t in (doc.get("fences") or {}).items():
                fences[name] = max(fences.get(name, 0), int(t))
            for rec in (doc.get("req_cache") or []):
                req_cache[rec["id"]] = (int(rec["code"]), rec["resp"])
        self.snapshot_rv = rv

        import collections
        tail: collections.deque = collections.deque(maxlen=event_ring)
        replayed = 0
        duplicates = 0
        drained_cids: set = set()
        if segments and cluster is None:
            cluster = FakeCluster()
        stop_replay = False
        for si, seg in enumerate(segments):
            if stop_replay:
                break
            last_segment = si == len(segments) - 1
            entries = self._scan_segment(seg)
            for ei, (lineno, offset, raw, complete) in enumerate(entries):
                rec, bad = parse_record(
                    raw.decode("utf-8", errors="replace"))
                if rec is None:
                    if bad == "blank":
                        continue
                    if last_segment and ei == len(entries) - 1 \
                            and not complete:
                        # the ONE tolerated shape: the final record of
                        # the final segment, missing its newline — a
                        # crash tore the append mid-write, so nothing
                        # after it was acked.  A final record WITH its
                        # newline was a complete append: a bad CRC
                        # there is bit rot on (possibly acked) state,
                        # which must refuse like any other corruption.
                        log.info("WAL %s torn tail at record %d "
                                 "(crash mid-append, %s); dropped",
                                 seg, lineno, bad)
                        break
                    self._handle_corruption(seg, lineno, offset, bad)
                    stop_replay = True
                    break
                seq = rec.get("q")
                if seq is not None:
                    seq = int(seq)
                    if seq <= last_seq:
                        # copy-restored / rotated-then-snapshotted
                        # duplicate: replay is idempotent by skipping
                        duplicates += 1
                        continue
                    if seq > last_seq + 1 and (last_seq or seq > 1):
                        # records are MISSING mid-stream — replaying
                        # past the hole would apply later state onto
                        # a base that never existed.  last_seq == 0
                        # with a first record past q=1 is the same
                        # hole (a lost first segment / deleted
                        # snapshot), not a fresh history.
                        self._handle_corruption(
                            seg, lineno, offset,
                            f"sequence gap {last_seq}->{seq}")
                        stop_replay = True
                        break
                    last_seq = seq
                kind = rec.get("k")
                if kind == "_probe":
                    continue            # heal liveness marker, no state
                if kind == "_lease":
                    o = rec["o"]
                    if o.get("term"):
                        lease_terms[o["name"]] = max(
                            lease_terms.get(o["name"], 0),
                            int(o["term"]))
                    if o.get("holder"):
                        leases[o["name"]] = (o["holder"],
                                             float(o["expires_wall"]))
                    else:
                        leases.pop(o["name"], None)
                elif kind == "_fence":
                    o = rec["o"]
                    fences[o["name"]] = max(
                        fences.get(o["name"], 0), int(o.get("term", 0)))
                elif kind == "_drain":
                    # collected, applied AFTER the loop: a drained
                    # command's add event may appear on either side
                    # of this record in the file (the add's journal
                    # write races the drain's), and cid filtering is
                    # order-independent
                    drained_cids.update(rec["o"].get("cids") or [])
                elif kind == "_req":
                    o = rec["o"]
                    req_cache[o["id"]] = (int(o["code"]), o["resp"])
                    while len(req_cache) > REQ_CACHE:
                        req_cache.pop(next(iter(req_cache)))
                else:
                    erv = int(rec.get("rv", 0))
                    if erv <= self.snapshot_rv:
                        continue    # rotated-then-snapshotted duplicate
                    apply_event(cluster, kind, rec["o"])
                    rv = max(rv, erv)
                    tail.append((erv, kind, rec["o"]))
                replayed += 1
        if duplicates:
            metrics.inc("server_wal_dropped_records_total",
                        value=float(duplicates), reason="duplicate-seq")
            log.warning("WAL replay skipped %d duplicate record(s) "
                        "(copy-restored segment?)", duplicates)
        if drained_cids:
            cluster.commands = [
                c for c in cluster.commands
                if not (isinstance(c, dict)
                        and c.get("cid") in drained_cids)]
        # drop expired leases now so the boot doesn't resurrect stale
        # holders (live ones rebase onto the monotonic clock upstairs)
        # vtplint: disable=wall-clock (the DISK carries wall expiries by contract; live ones rebase onto monotonic at boot)
        now = time.time()
        leases = {n: (h, exp) for n, (h, exp) in leases.items()
                  if exp > now}

        self._seq = last_seq
        # everything replayed is durable; the ship ring starts empty
        # (a follower past this seq tails, an older one bootstraps)
        self.synced_seq = last_seq
        self.replay_records = replayed
        self.replay_seconds = time.perf_counter() - t0
        if had_state:
            metrics.observe("server_replay_seconds", self.replay_seconds)
            metrics.set_gauge("server_replay_records", replayed)
        epoch = self._bump_epoch(continuous=had_state)
        # everything replayed IS durable: the new incarnation's synced
        # horizon starts at the recovered rv
        self._tail_rv = self.synced_rv = rv
        self._open_new_segment()
        self.recovery = Recovery(cluster, rv, list(tail), leases,
                                 req_cache, epoch, replayed,
                                 self.replay_seconds,
                                 lease_terms=lease_terms, fences=fences)
        return self.recovery

    def _open_new_segment(self) -> None:
        with self._lock:
            self._open_segment_locked()

    def _open_segment_locked(self) -> None:
        if self._file is not None:
            self._file.close()
        self._seg_seq += 1
        existing = self._segments()
        if existing:
            last = os.path.basename(existing[-1])
            try:
                self._seg_seq = int(
                    last[len(WAL_PREFIX):-len(".log")]) + 1
            except ValueError:
                pass
        path = os.path.join(self.dir,
                            f"{WAL_PREFIX}{self._seg_seq:08d}.log")
        self._file = self.vfs.open_append(path)

    # -- hot path ------------------------------------------------------

    def _poison(self, reason: str) -> None:
        from volcano_tpu import metrics
        if not self.poisoned:
            self.poisoned = reason
            metrics.set_gauge("server_readonly", 1.0)
            log.error("store POISONED for writes (%s): degrading to "
                      "read-only — writes 503 until heal() succeeds; "
                      "the failed fsync/append is NOT retried "
                      "(fsyncgate: a retried fsync can falsely "
                      "succeed over lost data)", reason)

    def append(self, rec: dict) -> None:
        """Buffer one record onto the live segment (no fsync here —
        commit() is the durability barrier the ack path calls).

        Never raises: a write failure (ENOSPC, injected torn write)
        poisons the store instead — the caller's commit() then fails
        the ack.  Poisoned appends are dropped and counted; the heal
        snapshot recaptures the in-memory state wholesale, so nothing
        acked is ever built on a dropped record."""
        from volcano_tpu import metrics
        with self._lock:
            if self.poisoned:
                metrics.inc("server_wal_dropped_records_total",
                            reason="readonly")
                return
            seq = self._seq + 1
            line = frame_record(rec, seq)
            try:
                self.vfs.write(self._file, line)
            except OSError as e:
                self._poison(f"append:{getattr(e, 'strerror', e)}")
                metrics.inc("server_wal_dropped_records_total",
                            reason="append-error")
                return
            self._seq = seq
            self._appended += 1
            self.wal_records += 1
            self.wal_bytes += len(line)
            self._ship.append((seq, line))
            if "rv" in rec:
                self._tail_rv = max(self._tail_rv, rec["rv"])

    def append_event(self, rv: int, kind: str, payload) -> None:
        self.append({"rv": rv, "k": kind, "o": payload})

    def append_shipped(self, line: str, seq: int, rv: int) -> None:
        """Append one leader-framed WAL line verbatim (follower path):
        the record keeps the LEADER's sequence number, so a promoted
        follower's log is seq-continuous with the group history and
        its own recover()/shipping work unchanged.  The caller
        (StateServer.apply_shipped) has already CRC-verified the line
        and checked seq continuity against synced_seq.

        Raises ReadOnlyError when this replica's own disk is poisoned:
        a follower that cannot durably apply must NOT advance its
        position — its advertised lag grows truthfully instead."""
        if not line.endswith("\n"):
            line += "\n"
        with self._lock:
            if self.poisoned:
                raise ReadOnlyError(self.poisoned)
            try:
                self.vfs.write(self._file, line)
            except OSError as e:
                self._poison(f"append:{getattr(e, 'strerror', e)}")
                raise ReadOnlyError(self.poisoned) from None
            self._seq = seq
            self._appended += 1
            self.wal_records += 1
            self.wal_bytes += len(line)
            self._ship.append((seq, line))
            if rv:
                self._tail_rv = max(self._tail_rv, rv)

    def ship_since(self, since_seq: int, limit: int = 2048) -> dict:
        """Framed records with since_seq < seq <= synced_seq for a
        follower long-poll.  resync=True when the follower's position
        fell off the (volatile) ship ring or is ahead of this store's
        history — only a replica-snapshot bootstrap recovers."""
        import itertools
        with self._lock:
            synced = self.synced_seq
            if since_seq > synced:
                return {"records": [], "last_seq": synced,
                        "resync": True}
            earliest = self._ship[0][0] if self._ship else synced + 1
            if since_seq + 1 < earliest:
                return {"records": [], "last_seq": synced,
                        "resync": True}
            # ring seqs are contiguous: the suffix starts at a known
            # offset — never scan the whole (up to 50k) ring per poll
            start = max(0, since_seq - earliest + 1)
            records = []
            for seq, line in itertools.islice(self._ship, start,
                                              start + limit):
                if seq > synced:
                    break
                records.append(line)
            return {"records": records, "last_seq": synced,
                    "resync": False}

    def snapshot_gate(self):
        """The compaction lock as a context manager, for callers that
        must pin the LOCK HIERARCHY from outside: _snap_lock is the
        OUTERMOST lock (snapshot()/heal() hold it while capturing
        state under the server lock), so any path that reaches this
        store while already holding the server lock must take the
        gate FIRST.  install_replica_snapshot is that path — taking
        _snap_lock inside the server lock deadlocked against a
        concurrent compaction (found by analysis/lockaudit.py: the
        wal-compactor thread holds _snap_lock wanting the server
        lock for capture while the follower tail thread holds the
        server lock wanting _snap_lock)."""
        return self._snap_lock

    def reset_from_snapshot(self, doc: dict, epoch: str) -> dict:
        """Install a replica snapshot wholesale (follower bootstrap /
        epoch-term-mismatch full re-sync): local WAL segments are
        DISCARDED (the leader's history supersedes them), the doc
        lands as the local snapshot atomically, and the seq/rv
        counters jump to the leader's horizon.  Returns the doc.

        Caller MUST hold snapshot_gate() (the lock-hierarchy contract
        above); only the store's inner lock is taken here."""
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
            for seg in self._segments():
                try:
                    os.remove(seg)
                except OSError:
                    log.warning("could not remove superseded WAL "
                                "%s", seg)
            doc = dict(doc)
            doc["format"] = SNAPSHOT_FORMAT
            # vtplint: disable=wall-clock (operator-facing snapshot stamp, never a deadline)
            doc["saved_at"] = time.time()
            atomic_write_json(
                os.path.join(self.dir, SNAPSHOT_FILE), doc)
            base, _, boot = epoch.rpartition(".")
            try:
                boot_n = int(boot)
            except ValueError:
                base, boot_n = epoch, 0
            atomic_write_json(os.path.join(self.dir, EPOCH_FILE),
                              {"base": base or epoch,
                               "boot": boot_n})
            self._seq = self.synced_seq = int(doc.get("wal_seq", 0))
            rv = int(doc.get("rv", 0))
            self._tail_rv = self.synced_rv = rv
            self.snapshot_rv = rv
            self.snapshot_at = doc["saved_at"]
            self._appended = self._synced_marker = 0
            self.wal_records = 0
            self.wal_bytes = 0
            self._ship.clear()
            self.poisoned = ""
            self._open_segment_locked()
        return doc

    def commit(self) -> int:
        """Make every appended record durable; returns the new synced
        rv horizon.  Group commit: the fsync that one thread pays
        covers every record appended before it, so concurrent callers
        mostly return on the marker check without syncing again.

        Raises ReadOnlyError when the store is (or just became)
        poisoned: a failed fsync is NEVER retried — the records it
        covered are in an unknown state, and only heal()'s fresh
        segment + full snapshot restores durability."""
        from volcano_tpu import metrics
        with self._lock:
            if self.poisoned:
                raise ReadOnlyError(self.poisoned)
            target = self._appended
            if self._synced_marker >= target:
                return self.synced_rv
            t0 = time.perf_counter()
            try:
                self.vfs.fsync(self._file)
            except OSError as e:
                self._poison(f"fsync:{getattr(e, 'strerror', e)}")
                raise ReadOnlyError(self.poisoned) from None
            # marker/tail re-read under the SAME lock hold: anything
            # appended while we blocked in fsync hit the file before
            # this flush? no — but it will be covered by ITS caller's
            # commit; only what was appended at flush time is synced
            self._synced_marker = target
            self.synced_rv = self._tail_rv
            self.synced_seq = self._seq
            self.last_fsync_s = time.perf_counter() - t0
            metrics.observe("server_wal_fsync_seconds", self.last_fsync_s)
            return self.synced_rv

    def should_snapshot(self) -> bool:
        with self._lock:
            return (self.wal_records >= self.snapshot_every_records or
                    self.wal_bytes >= self.snapshot_every_bytes)

    # -- read-only degrade + heal --------------------------------------

    def heal(self, capture: Callable[[], dict]) -> bool:
        """Attempt to leave read-only mode.  Protocol:

          1. rotate to a FRESH segment (the poisoned file's contents
             are presumed lost — never fsync it again);
          2. probe the new segment with a real append + fsync through
             the same VFS seam (a still-sick disk fails here and we
             stay read-only);
          3. capture() + atomically write a FULL snapshot — the
             in-memory state (including mutations whose journal
             records were dropped while poisoned; none were acked)
             becomes durable wholesale;
          4. delete the frozen segments, clear the poison.

        Returns True when writable again; rv is untouched throughout,
        so the counter stays monotonic across the whole episode."""
        from volcano_tpu import metrics
        with self._snap_lock:
            if not self.poisoned:
                return True
            with self._lock:
                try:
                    self._open_segment_locked()
                    seq = self._seq + 1
                    self.vfs.write(self._file, frame_record(
                        {"k": "_probe"}, seq))
                    self.vfs.fsync(self._file)
                    self._seq = seq
                except OSError as e:
                    log.info("heal probe failed (%s); staying "
                             "read-only", e)
                    return False
                frozen = [s for s in self._segments()
                          if s != self._file.name]
                self._appended = self._synced_marker = 0
                self.wal_records = 0
                self.wal_bytes = 0
                # the poisoned segments' records are presumed lost and
                # the heal snapshot recaptures state wholesale: a
                # follower mid-tail cannot prove continuity across the
                # episode, so clear the ship ring — its next poll
                # falls off and bootstraps from the heal snapshot
                self._ship.clear()
                self.synced_seq = self._seq
                # while poisoned, appends drop without consuming seq,
                # so the probe's is the horizon (same freeze-time rule
                # as snapshot()).  Stamp the snapshot one BELOW it:
                # the probe record itself stays in the live segment,
                # and a wal_seq equal to its q would make the next
                # boot flag it as a copy-restored duplicate — false
                # corruption noise on exactly the post-incident
                # forensics path.  At wal_seq = probe_seq - 1 the
                # probe replays in-sequence and is skipped by kind.
                probe_seq = self._seq
            try:
                doc = capture()
                doc["format"] = SNAPSHOT_FORMAT
                # vtplint: disable=wall-clock (operator-facing snapshot stamp, never a deadline)
                doc["saved_at"] = time.time()
                doc["wal_seq"] = probe_seq - 1
                atomic_write_json(os.path.join(self.dir, SNAPSHOT_FILE),
                                  doc)
            except OSError as e:
                log.info("heal snapshot failed (%s); staying "
                         "read-only", e)
                return False
            with self._lock:
                self.snapshot_rv = int(doc.get("rv", 0))
                self.snapshot_at = doc["saved_at"]
                # the snapshot covers every event up to its rv: the
                # durable horizon jumps there, releasing the events
                # that were stuck behind the poisoned WAL
                self._tail_rv = max(self._tail_rv, self.snapshot_rv)
                self.synced_rv = self._tail_rv
                was = self.poisoned
                self.poisoned = ""
            for seg in frozen:
                try:
                    os.remove(seg)
                except OSError:
                    log.warning("could not remove poisoned WAL %s", seg)
            metrics.set_gauge("server_readonly", 0.0)
            metrics.inc("server_snapshot_total")
            log.warning("store HEALED (was read-only: %s): fresh "
                        "segment probed, full snapshot at rv %d, "
                        "writable again", was, self.snapshot_rv)
            return True

    # -- compaction ----------------------------------------------------

    def snapshot(self, capture: Callable[[], dict]) -> dict:
        """Write a snapshot and compact the WAL.

        Order of operations is the crash-safety argument:
          1. rotate to a fresh segment (old ones frozen, still on disk)
          2. capture() the store state — at a rv >= everything in the
             frozen segments, because rotation happened first
          3. atomic-write the snapshot
          4. delete the frozen segments
        A crash after any step replays to the same state: old snapshot
        + all segments (1-3), or new snapshot + live segment with the
        pre-capture records skipped by their rv (after 3).

        The freeze (fsync old segment → rotate → reset the commit
        markers) happens under ONE continuous lock hold: an append
        sneaking in between the fsync and the marker reset would land
        un-fsync'd in the frozen segment while its commit() no-ops on
        the zeroed marker — an acked-but-volatile write, exactly what
        this module exists to forbid."""
        from volcano_tpu import metrics
        with self._snap_lock:
            t0 = time.perf_counter()
            with self._lock:
                if self.poisoned:
                    # no compaction while read-only: heal() owns the
                    # recovery snapshot (fsyncing the poisoned file
                    # here would be exactly the forbidden retry)
                    raise ReadOnlyError(self.poisoned)
                try:
                    self.vfs.fsync(self._file)
                except OSError as e:
                    self._poison(f"fsync:{getattr(e, 'strerror', e)}")
                    raise ReadOnlyError(self.poisoned) from None
                self.synced_rv = self._tail_rv
                self.synced_seq = self._seq
                frozen = self._segments()
                self._open_segment_locked()
                self._appended = self._synced_marker = 0
                self.wal_records = 0
                self.wal_bytes = 0
                # seq horizon AT THE FREEZE, under the same lock hold:
                # everything <= frozen_seq is in the frozen segments
                # the capture() below covers.  Reading self._seq after
                # capture would fold in records appended to the NEW
                # live segment in the meantime — recovery would then
                # skip them as "covered" while the snapshot lacks
                # them: a silently lost acked write.
                frozen_seq = self._seq

            doc = capture()
            doc["format"] = SNAPSHOT_FORMAT
            # vtplint: disable=wall-clock (operator-facing snapshot stamp, never a deadline)
            doc["saved_at"] = time.time()
            doc["wal_seq"] = frozen_seq
            atomic_write_json(os.path.join(self.dir, SNAPSHOT_FILE),
                              doc)
            with self._lock:
                self.snapshot_rv = int(doc.get("rv", 0))
                self.snapshot_at = doc["saved_at"]
            for seg in frozen:
                try:
                    os.remove(seg)
                except OSError:
                    log.warning("could not remove compacted WAL %s",
                                seg)
            dt = time.perf_counter() - t0
        metrics.observe("server_snapshot_seconds", dt)
        metrics.inc("server_snapshot_total")
        metrics.set_gauge("server_snapshot_rv", self.snapshot_rv)
        return doc

    # -- status --------------------------------------------------------

    def status(self) -> dict:
        from volcano_tpu import metrics
        with self._lock:
            st = {
                "dir": self.dir,
                "wal_records": self.wal_records,
                "wal_bytes": self.wal_bytes,
                "wal_seq": self._seq,
                "synced_rv": self.synced_rv,
                "snapshot_rv": self.snapshot_rv,
                # vtplint: disable=wall-clock (status display only; snapshot_at is a wall stamp)
                "snapshot_age_s": (round(time.time() - self.snapshot_at, 3)
                                   if self.snapshot_at else None),
                "last_fsync_s": round(self.last_fsync_s, 6),
                "replay_records": self.replay_records,
                "replay_seconds": round(self.replay_seconds, 4),
                "readonly": self.poisoned,
            }
        metrics.set_gauge("server_wal_records", st["wal_records"])
        metrics.set_gauge("server_wal_bytes", st["wal_bytes"])
        return st

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                if not self.poisoned:
                    try:
                        self.vfs.fsync(self._file)
                    except OSError as e:
                        self._poison(
                            f"fsync:{getattr(e, 'strerror', e)}")
                self._file.close()
                self._file = None
