"""Crash-safe persistence for the state server: WAL + snapshots.

The authoritative store used to be an in-memory FakeCluster whose only
durability was a pickle written on graceful shutdown — a SIGKILL/OOM
lost every acked bind, podgroup phase, quarantine TTL and lease, and
restarted the event log so every mirror's delta resync silently
desynced.  The reference keeps all truth behind an apiserver/etcd that
journals before acking; this module gives volcano-tpu the same
contract (docs/design/durability.md):

  * every store mutation appends ONE record to a write-ahead log and
    is fsync'd before the HTTP ack (group commit: concurrent handler
    threads share one fsync barrier, so a 256-bind burst pays ~1
    fsync, not 256);
  * a periodic snapshot (write-temp + atomic rename + dir fsync)
    compacts the log: snapshot = full store dump + last rv + epoch;
    WAL segments wholly covered by a durable snapshot are deleted;
  * boot replays snapshot-then-WAL-tail, resumes the rv counter
    monotonically, reseeds the watch event ring from the tail, and
    bumps the boot half of the epoch ("BASE.BOOT") so mirrors KNOW a
    restart happened — same BASE means the history is WAL-continuous
    and a delta resync across the restart is exact; a different BASE
    (fresh dir, legacy pickle boot) forces a full re-list.

Record format — one JSON line per record, self-delimiting so a crash
mid-append truncates to the last complete line:

    {"rv": N, "k": kind, "o": <codec payload>}       store event
    {"k": "_lease", "o": {name, holder, expires_wall}} lease CAS
    {"k": "_drain", "o": {"target": key}}              command drain
    {"k": "_req",  "o": {"id":..,"code":..,"resp":..}} idempotency key

Only store events carry rv (they are the watch stream); the private
records replay in file order.  Leases persist wall-clock expiry and
are rebased onto the monotonic clock at boot, so a restarted server
refuses a second leader inside an old holder's TTL while a wall-clock
jump can never mass-expire (or immortalize) live leases.
"""

from __future__ import annotations

import io
import json
import logging
import os
import threading
import time
import uuid
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

log = logging.getLogger(__name__)

SNAPSHOT_FILE = "snapshot.json"
EPOCH_FILE = "epoch.json"
WAL_PREFIX = "wal-"
SNAPSHOT_FORMAT = "volcano-tpu-snapshot-v1"
# compaction thresholds: snapshot once the live WAL holds this many
# records or bytes (whichever first) — bounds both replay time and
# disk growth without paying a full store dump per mutation
SNAPSHOT_EVERY_RECORDS = 20_000
SNAPSHOT_EVERY_BYTES = 64 * 1024 * 1024
# replayed idempotency keys retained (snapshot + memory): a retried
# mutation whose first attempt committed before a crash must find its
# recorded response, not double-apply
REQ_CACHE = 2048


class Recovery(NamedTuple):
    cluster: Optional[object]      # FakeCluster, or None (nothing on disk)
    rv: int                        # resume point for the event counter
    events: List[Tuple[int, str, object]]   # ring tail [(rv, kind, payload)]
    leases: Dict[str, Tuple[str, float]]    # name -> (holder, expires_wall)
    req_cache: "Dict[str, Tuple[int, object]]"  # req id -> (code, payload)
    epoch: str                     # bumped incarnation id "BASE.BOOT"
    replay_records: int
    replay_seconds: float


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:            # platform without dir fds
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write_json(path: str, doc: dict) -> None:
    """write-temp + fsync + atomic rename + dir fsync — the one
    snapshot writer every save path routes through (including the
    legacy --state graceful save), so a crash mid-save can never
    leave a torn file where the last good state was."""
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, separators=(",", ":"))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(os.path.abspath(path)) or ".")


def decode_stores_into(cluster, stores: dict) -> None:
    """Fold an encoded snapshot `stores` dict (the /snapshot payload
    shape) into a FakeCluster's attribute stores."""
    from volcano_tpu.api import codec
    from volcano_tpu.cache.kinds import KINDS
    for kind, spec in KINDS.items():
        store = {k: codec.decode(enc)
                 for k, enc in stores.get(kind, {}).items()}
        if store or not getattr(cluster, spec.attr, None):
            # merge over construction defaults (e.g. the default
            # queue) only when the snapshot actually carried the kind
            getattr(cluster, spec.attr).update(store)
    cmds = codec.decode(stores.get("_commands", [])) or []
    cluster.commands = list(cmds)


def apply_event(cluster, kind: str, payload) -> None:
    """Replay ONE WAL store event onto the authoritative store —
    the server-side twin of RemoteCluster._apply_batch: no admission
    (it already ran before the event was logged), no watchers (none
    are attached at boot)."""
    from volcano_tpu.api import codec
    from volcano_tpu.cache.kinds import KINDS
    obj = codec.decode(payload)
    deleted = kind.endswith("_deleted")
    base = kind[:-len("_deleted")] if deleted else kind
    spec = KINDS.get(base)
    if spec is not None:
        key = obj["key"] if spec.key_of is None else spec.key_of(obj)
        store = getattr(cluster, spec.attr)
        if deleted:
            store.pop(key, None)
        else:
            store[key] = obj if spec.key_of else obj["obj"]
    elif base == "command":
        cluster.commands.append(obj)
    # unknown kinds (a future version's events) replay as no-ops: the
    # snapshot that follows them will carry whatever they meant


def load_cluster_file(path: str):
    """Load a cluster state file in EITHER format: the legacy pickle
    or the snapshot JSON the graceful save now writes (--state stays
    working as an alias across the format change).  Returns a
    FakeCluster with no admission chain attached."""
    import pickle
    with open(path, "rb") as f:
        head = f.read(1)
        f.seek(0)
        if head != b"{":
            return pickle.load(f)
        doc = json.load(io.TextIOWrapper(f, encoding="utf-8"))
    from volcano_tpu.cache.fake_cluster import FakeCluster
    cluster = FakeCluster()
    decode_stores_into(cluster, doc.get("stores", {}))
    return cluster


class DurableStore:
    """Owns the WAL segments + snapshot of one state-server data dir."""

    def __init__(self, data_dir: str,
                 snapshot_every_records: int = SNAPSHOT_EVERY_RECORDS,
                 snapshot_every_bytes: int = SNAPSHOT_EVERY_BYTES):
        self.dir = os.path.abspath(data_dir)
        os.makedirs(self.dir, exist_ok=True)
        self.snapshot_every_records = snapshot_every_records
        self.snapshot_every_bytes = snapshot_every_bytes
        self._lock = threading.Lock()     # file handle + counters
        # serializes whole snapshot() sequences: the background
        # compactor and the graceful-save path must never interleave
        # rotate/capture/rename/delete (a slower older capture could
        # overwrite a newer snapshot AFTER the newer call deleted the
        # WAL segments covering the difference)
        self._snap_lock = threading.Lock()
        self._file: Optional[io.TextIOBase] = None
        self._seg_seq = 0
        self._appended = 0                # records since last fsync mark
        self._synced_marker = 0
        self._tail_rv = 0                 # last store-event rv appended
        self.synced_rv = 0                # last store-event rv fsync'd
        self.wal_records = 0              # records in live segments
        self.wal_bytes = 0
        self.snapshot_rv = 0
        self.snapshot_at = 0.0            # wall time of last snapshot
        self.last_fsync_s = 0.0
        self.replay_records = 0
        self.replay_seconds = 0.0
        self.recovery: Optional[Recovery] = None

    # -- boot ----------------------------------------------------------

    def _segments(self) -> List[str]:
        try:
            names = sorted(n for n in os.listdir(self.dir)
                           if n.startswith(WAL_PREFIX)
                           and n.endswith(".log"))
        except FileNotFoundError:
            return []
        return [os.path.join(self.dir, n) for n in names]

    def _bump_epoch(self, continuous: bool) -> str:
        """Read/advance the incarnation id.  BASE survives as long as
        the WAL history is continuous (mirrors may delta-resync across
        the restart); a dir with no durable state mints a fresh BASE
        (mirrors must full re-list — their rv space is meaningless
        here)."""
        path = os.path.join(self.dir, EPOCH_FILE)
        base, boot = "", 0
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
            base, boot = doc.get("base", ""), int(doc.get("boot", 0))
        except (OSError, ValueError):
            pass
        if not base or not continuous:
            base = uuid.uuid4().hex[:12]
        boot += 1
        atomic_write_json(path, {"base": base, "boot": boot})
        return f"{base}.{boot}"

    def recover(self, event_ring: int = 100_000) -> Recovery:
        """Snapshot + WAL-tail replay; opens a fresh live segment.
        Returns cluster=None when the dir held no durable state (the
        caller seeds it and writes the initial snapshot)."""
        from volcano_tpu import metrics
        from volcano_tpu.cache.fake_cluster import FakeCluster

        t0 = time.perf_counter()
        snap_path = os.path.join(self.dir, SNAPSHOT_FILE)
        doc = None
        if os.path.exists(snap_path):
            try:
                with open(snap_path, encoding="utf-8") as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                log.exception("snapshot %s unreadable; replaying WAL "
                              "from scratch", snap_path)
        segments = self._segments()
        had_state = doc is not None or bool(segments)

        cluster = None
        rv = 0
        leases: Dict[str, Tuple[str, float]] = {}
        req_cache: Dict[str, Tuple[int, object]] = {}
        if doc is not None:
            cluster = FakeCluster()
            decode_stores_into(cluster, doc.get("stores", {}))
            rv = int(doc.get("rv", 0))
            for name, rec in (doc.get("leases") or {}).items():
                leases[name] = (rec["holder"], float(rec["expires_wall"]))
            for rec in (doc.get("req_cache") or []):
                req_cache[rec["id"]] = (int(rec["code"]), rec["resp"])
        self.snapshot_rv = rv

        import collections
        tail: collections.deque = collections.deque(maxlen=event_ring)
        replayed = 0
        drained_cids: set = set()
        if segments and cluster is None:
            cluster = FakeCluster()
        for i, seg in enumerate(segments):
            last = i == len(segments) - 1
            for rec in self._read_segment(seg, tolerate_tail=last):
                kind = rec.get("k")
                if kind == "_lease":
                    o = rec["o"]
                    if o.get("holder"):
                        leases[o["name"]] = (o["holder"],
                                             float(o["expires_wall"]))
                    else:
                        leases.pop(o["name"], None)
                elif kind == "_drain":
                    # collected, applied AFTER the loop: a drained
                    # command's add event may appear on either side
                    # of this record in the file (the add's journal
                    # write races the drain's), and cid filtering is
                    # order-independent
                    drained_cids.update(rec["o"].get("cids") or [])
                elif kind == "_req":
                    o = rec["o"]
                    req_cache[o["id"]] = (int(o["code"]), o["resp"])
                    while len(req_cache) > REQ_CACHE:
                        req_cache.pop(next(iter(req_cache)))
                else:
                    erv = int(rec.get("rv", 0))
                    if erv <= self.snapshot_rv:
                        continue    # rotated-then-snapshotted duplicate
                    apply_event(cluster, kind, rec["o"])
                    rv = max(rv, erv)
                    tail.append((erv, kind, rec["o"]))
                replayed += 1
        if drained_cids:
            cluster.commands = [
                c for c in cluster.commands
                if not (isinstance(c, dict)
                        and c.get("cid") in drained_cids)]
        # drop expired leases now so the boot doesn't resurrect stale
        # holders (live ones rebase onto the monotonic clock upstairs)
        now = time.time()
        leases = {n: (h, exp) for n, (h, exp) in leases.items()
                  if exp > now}

        self.replay_records = replayed
        self.replay_seconds = time.perf_counter() - t0
        if had_state:
            metrics.observe("server_replay_seconds", self.replay_seconds)
            metrics.set_gauge("server_replay_records", replayed)
        epoch = self._bump_epoch(continuous=had_state)
        # everything replayed IS durable: the new incarnation's synced
        # horizon starts at the recovered rv
        self._tail_rv = self.synced_rv = rv
        self._open_new_segment()
        self.recovery = Recovery(cluster, rv, list(tail), leases,
                                 req_cache, epoch, replayed,
                                 self.replay_seconds)
        return self.recovery

    @staticmethod
    def _read_segment(path: str, tolerate_tail: bool):
        """Yield records; a torn/corrupt line ends the segment — only
        tolerated silently on the LIVE segment's tail (crash mid-
        append), logged loudly anywhere else (real corruption: the
        replay still applies the consistent prefix)."""
        try:
            with open(path, encoding="utf-8", errors="replace") as f:
                for lineno, line in enumerate(f, 1):
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        yield json.loads(line)
                    except ValueError:
                        if not tolerate_tail:
                            log.error("WAL %s corrupt at line %d; "
                                      "replay stops there", path, lineno)
                        else:
                            log.info("WAL %s torn tail at line %d "
                                     "(crash mid-append); dropped",
                                     path, lineno)
                        return
        except OSError:
            log.exception("WAL segment %s unreadable", path)

    def _open_new_segment(self) -> None:
        with self._lock:
            self._open_segment_locked()

    def _open_segment_locked(self) -> None:
        if self._file is not None:
            self._file.close()
        self._seg_seq += 1
        existing = self._segments()
        if existing:
            last = os.path.basename(existing[-1])
            try:
                self._seg_seq = int(
                    last[len(WAL_PREFIX):-len(".log")]) + 1
            except ValueError:
                pass
        path = os.path.join(self.dir,
                            f"{WAL_PREFIX}{self._seg_seq:08d}.log")
        self._file = open(path, "a", encoding="utf-8")

    # -- hot path ------------------------------------------------------

    def append(self, rec: dict) -> None:
        """Buffer one record onto the live segment (no fsync here —
        commit() is the durability barrier the ack path calls)."""
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        with self._lock:
            self._file.write(line)
            self._appended += 1
            self.wal_records += 1
            self.wal_bytes += len(line)
            if "rv" in rec:
                self._tail_rv = max(self._tail_rv, rec["rv"])

    def append_event(self, rv: int, kind: str, payload) -> None:
        self.append({"rv": rv, "k": kind, "o": payload})

    def commit(self) -> int:
        """Make every appended record durable; returns the new synced
        rv horizon.  Group commit: the fsync that one thread pays
        covers every record appended before it, so concurrent callers
        mostly return on the marker check without syncing again."""
        from volcano_tpu import metrics
        with self._lock:
            target = self._appended
            if self._synced_marker >= target:
                return self.synced_rv
            t0 = time.perf_counter()
            self._file.flush()
            os.fsync(self._file.fileno())
            # marker/tail re-read under the SAME lock hold: anything
            # appended while we blocked in fsync hit the file before
            # this flush? no — but it will be covered by ITS caller's
            # commit; only what was appended at flush time is synced
            self._synced_marker = target
            self.synced_rv = self._tail_rv
            self.last_fsync_s = time.perf_counter() - t0
            metrics.observe("server_wal_fsync_seconds", self.last_fsync_s)
            return self.synced_rv

    def should_snapshot(self) -> bool:
        with self._lock:
            return (self.wal_records >= self.snapshot_every_records or
                    self.wal_bytes >= self.snapshot_every_bytes)

    # -- compaction ----------------------------------------------------

    def snapshot(self, capture: Callable[[], dict]) -> dict:
        """Write a snapshot and compact the WAL.

        Order of operations is the crash-safety argument:
          1. rotate to a fresh segment (old ones frozen, still on disk)
          2. capture() the store state — at a rv >= everything in the
             frozen segments, because rotation happened first
          3. atomic-write the snapshot
          4. delete the frozen segments
        A crash after any step replays to the same state: old snapshot
        + all segments (1-3), or new snapshot + live segment with the
        pre-capture records skipped by their rv (after 3).

        The freeze (fsync old segment → rotate → reset the commit
        markers) happens under ONE continuous lock hold: an append
        sneaking in between the fsync and the marker reset would land
        un-fsync'd in the frozen segment while its commit() no-ops on
        the zeroed marker — an acked-but-volatile write, exactly what
        this module exists to forbid."""
        from volcano_tpu import metrics
        with self._snap_lock:
            t0 = time.perf_counter()
            with self._lock:
                self._file.flush()
                os.fsync(self._file.fileno())
                self.synced_rv = self._tail_rv
                frozen = self._segments()
                self._open_segment_locked()
                self._appended = self._synced_marker = 0
                self.wal_records = 0
                self.wal_bytes = 0

            doc = capture()
            doc["format"] = SNAPSHOT_FORMAT
            doc["saved_at"] = time.time()
            atomic_write_json(os.path.join(self.dir, SNAPSHOT_FILE),
                              doc)
            with self._lock:
                self.snapshot_rv = int(doc.get("rv", 0))
                self.snapshot_at = doc["saved_at"]
            for seg in frozen:
                try:
                    os.remove(seg)
                except OSError:
                    log.warning("could not remove compacted WAL %s",
                                seg)
            dt = time.perf_counter() - t0
        metrics.observe("server_snapshot_seconds", dt)
        metrics.inc("server_snapshot_total")
        metrics.set_gauge("server_snapshot_rv", self.snapshot_rv)
        return doc

    # -- status --------------------------------------------------------

    def status(self) -> dict:
        from volcano_tpu import metrics
        with self._lock:
            st = {
                "dir": self.dir,
                "wal_records": self.wal_records,
                "wal_bytes": self.wal_bytes,
                "synced_rv": self.synced_rv,
                "snapshot_rv": self.snapshot_rv,
                "snapshot_age_s": (round(time.time() - self.snapshot_at, 3)
                                   if self.snapshot_at else None),
                "last_fsync_s": round(self.last_fsync_s, 6),
                "replay_records": self.replay_records,
                "replay_seconds": round(self.replay_seconds, 4),
            }
        metrics.set_gauge("server_wal_records", st["wal_records"])
        metrics.set_gauge("server_wal_bytes", st["wal_bytes"])
        return st

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.flush()
                os.fsync(self._file.fileno())
                self._file.close()
                self._file = None
