"""Audit-log latency exporter — benchmark-layer parity.

The reference measures scheduling latency OUTSIDE the scheduler, from
apiserver audit logs: microsecond `pods/binding` timestamps minus pod
creation timestamps (third_party/kube-apiserver-audit-exporter/
exporter/metrics.go:32-38 — pod_scheduling_latency_seconds and
batchjob_completion_latency_seconds).  This exporter does the same
against the state server's /audit trail: it needs no cooperation from
the scheduler, so the numbers it reports are ground truth for the wire
control plane, not self-reported.

Usage:
    exp = AuditExporter("http://127.0.0.1:8080")
    exp.poll()              # incremental; call on a timer
    exp.pod_latencies()     # {pod_key: seconds}
Observations also land in volcano_tpu.metrics under
pod_scheduling_latency_seconds / batchjob_completion_latency_seconds
so they ride the normal /metrics exposition.
"""

from __future__ import annotations

import logging
import urllib.request
from typing import Dict, List

from volcano_tpu import metrics

log = logging.getLogger(__name__)

TERMINAL_JOB_PHASES = {"Completed", "Failed", "Aborted"}
# completed (bound) pairs retained for pod_latencies(); beyond this the
# oldest measured pairs are dropped (observations already landed in the
# metrics registry, so nothing is lost from the histograms)
MAX_TRACKED = 100_000


class AuditExporter:
    def __init__(self, base_url: str, timeout: float = 5.0,
                 ca_cert: str = "", insecure: bool = False,
                 token: str = ""):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.token = token
        from volcano_tpu.server.tlsutil import client_ssl_context
        self._ssl_ctx = client_ssl_context(ca_cert, insecure)
        self._since = 0
        self._pod_created: Dict[str, float] = {}
        self._pod_bound: Dict[str, float] = {}
        self._job_created: Dict[str, float] = {}
        self._job_done: Dict[str, float] = {}
        # jobs first seen ALREADY terminal (exporter attached mid-run):
        # creation ts was seeded by the same record, so a completion
        # latency would read ~0 — excluded from observations/results
        self._seeded_terminal: set = set()
        self.lost_records = False   # sticky: a poll fell off the ring

    # -- collection ----------------------------------------------------

    def poll(self) -> int:
        """Fetch and fold new audit records (paging until drained);
        returns how many.  The server enables audit collection on the
        first poll, so start the exporter BEFORE the workload you want
        measured."""
        total = 0
        while True:
            url = f"{self.base_url}/audit?since={self._since}"
            headers = {"Accept-Encoding": "gzip"}   # 10k-record pages
            if self.token:
                headers["Authorization"] = f"Bearer {self.token}"
            req = urllib.request.Request(url, headers=headers)
            try:
                from volcano_tpu.server.httputil import read_json_body
                with urllib.request.urlopen(req, timeout=self.timeout,
                                            context=self._ssl_ctx
                                            ) as resp:
                    payload = read_json_body(resp)
            except Exception as e:  # noqa: BLE001 - exporter must not die
                log.warning("audit poll of %s failed: %s", url, e)
                break
            if payload.get("lost"):
                self.lost_records = True
                log.warning("audit ring wrapped between polls: some "
                            "records were lost; latencies may "
                            "undercount")
            records = payload.get("records", [])
            for rec in records:
                self._fold(rec)
            total += len(records)
            new_since = payload.get("idx", self._since)
            if not records or new_since <= self._since:
                self._since = new_since
                break
            self._since = new_since
        self._trim()
        return total

    def _fold(self, rec: dict) -> None:
        kind, key, ts = rec.get("kind"), rec.get("key"), rec.get("ts")
        if not key or ts is None:
            return
        if kind == "pod":
            if not rec.get("node"):
                # first sighting without a node = creation
                self._pod_created.setdefault(key, ts)
            elif key not in self._pod_bound:
                self._pod_bound[key] = ts
                created = self._pod_created.get(key)
                if created is not None:
                    metrics.observe("pod_scheduling_latency_seconds",
                                    ts - created)
        elif kind == "pod_deleted":
            # a recreated same-key pod is a NEW scheduling episode
            self._pod_created.pop(key, None)
            self._pod_bound.pop(key, None)
        elif kind == "vcjob":
            first_sighting = key not in self._job_created
            self._job_created.setdefault(key, ts)
            if rec.get("phase") in TERMINAL_JOB_PHASES and \
                    key not in self._job_done:
                self._job_done[key] = ts
                if first_sighting:
                    self._seeded_terminal.add(key)
                else:
                    metrics.observe(
                        "batchjob_completion_latency_seconds",
                        ts - self._job_created[key])
        elif kind == "vcjob_deleted":
            self._job_created.pop(key, None)
            self._job_done.pop(key, None)
            self._seeded_terminal.discard(key)

    def _trim(self) -> None:
        for store in (self._pod_created, self._pod_bound,
                      self._job_created, self._job_done):
            while len(store) > MAX_TRACKED:
                store.pop(next(iter(store)))    # oldest insertion

    # -- results -------------------------------------------------------

    def pod_latencies(self) -> Dict[str, float]:
        return {k: self._pod_bound[k] - self._pod_created[k]
                for k in self._pod_bound
                if k in self._pod_created}

    def job_completion_latencies(self) -> Dict[str, float]:
        return {k: self._job_done[k] - self._job_created[k]
                for k in self._job_done
                if k in self._job_created
                and k not in self._seeded_terminal}

    def quantile(self, q: float) -> float:
        import math
        lats: List[float] = sorted(self.pod_latencies().values())
        if not lats:
            return 0.0
        # nearest-rank: ceil(q*n)-1 (int(q*n) reads one rank high at
        # exact multiples)
        return lats[max(0, min(len(lats) - 1,
                               math.ceil(q * len(lats)) - 1))]
