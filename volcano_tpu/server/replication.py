"""Replicated control plane: WAL shipping, follower reads, election.

Every hardened layer so far — the WAL (PR 4), the chaos engine (PR 8)
— still funnels through ONE state-server process.  This module splits
the roles the way Singularity's planet-scale store does (arxiv
2202.07848): ONE elected leader accepts writes; N follower replicas
continuously replay the leader's fsync'd WAL and serve the read-heavy
traffic (watch mirrors, /traces, /leases, vtpctl, dashboards) at a
bounded, *advertised* staleness.  docs/design/replication.md is the
full protocol; the contract in one breath:

  * SHIPPING — followers long-poll ``GET /wal?since_seq=N`` on the
    leader; the response carries raw framed WAL lines (crc32hex +
    body), ONLY up to the leader's fsync horizon.  The follower
    re-verifies every record's CRC and sequence before appending it to
    its OWN WAL and fsyncing — a torn or bit-flipped shipped record is
    refused wholesale (never silently applied) and re-requested.  A
    follower behind the leader's ship ring (compaction, heal, fresh
    boot) bootstraps from ``GET /replica_snapshot`` then tails.
  * QUORUM COMMIT — with a replica group configured, the leader's ack
    barrier extends past its local fsync: a write is acked only once a
    commit quorum (majority of the group, leader included) holds it
    durably.  The quorum wait doubles as the fence: a partitioned
    leader cannot ack anything (writes 503 + Retry-After through the
    read-only degrade machinery), so a new leader elected on the other
    side can never lose an acked write.
  * STALENESS — a follower's visible rv is gated on its own fsync
    horizon exactly like the leader's (state_server._visible_rv), so
    no follower ever serves an rv it has not durably applied; its
    advertised lag is measured, not asserted.
  * ELECTION — terms extend the BASE.BOOT epoch machinery: the term is
    journaled per replica (term.json, atomic write) and every shipped
    batch carries the leader's term.  On leader silence past the TTL a
    follower campaigns at term+1; peers grant a vote only when they
    ALSO lost the leader, the candidate's WAL prefix is at least as
    long as theirs, and they have not voted this term.  A majority
    (counting the candidate) promotes: the new leader bumps the boot
    half of the epoch (same BASE — mirrors delta-resync across the
    promotion) and starts shipping at its term.  A deposed leader
    that lost its commit quorum probes the group, finds the higher
    term, demotes itself and full-resyncs as a follower.
  * WRITE ROUTING — any mutation hitting a follower is refused with
    the read-only 503 + Retry-After shape plus a ``leader`` hint;
    cache/remote_cluster.py re-routes to the hinted leader under the
    unified retry policy.

A two-replica group cannot distinguish leader death from partition,
so automatic promotion there needs the explicit --election-quorum 1
override (the lab/smoke configuration); three or more replicas elect
on true majorities.  The split-brain argument lives in the doc.
"""

from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional

log = logging.getLogger(__name__)

TERM_FILE = "term.json"
# follower tail long-poll ceiling; also the shipping heartbeat — a
# healthy idle group exchanges one empty batch per WAL_POLL_S.  Kept
# short: a blackholed poll is only noticed when its client timeout
# fires, so this bounds the leader-death detection latency
WAL_POLL_S = 2.0
# records per shipped batch: bounds both the response size and the
# follower's apply-then-fsync critical section
SHIP_BATCH = 2048


def http_json(method: str, url: str, payload=None, timeout: float = 10.0,
              token: str = ""):
    """One replication-plane RPC (stdlib only, gzip-aware).  Raises
    OSError/ValueError like any wire call; callers own the retry.
    Truncated/garbled responses (HTTPException — e.g. an injected
    connection reset cutting a /wal body mid-read) normalize to
    OSError too: the chaos conductor found a follower's tail thread
    dying on an uncaught IncompleteRead."""
    from http.client import HTTPException
    data = None
    if payload is not None:
        data = json.dumps(payload, separators=(",", ":")).encode()
    headers = {"Content-Type": "application/json",
               "Accept-Encoding": "gzip"}
    if token:
        headers["Authorization"] = f"Bearer {token}"
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            from volcano_tpu.server.httputil import read_json_body
            return read_json_body(resp)
    except urllib.error.HTTPError as e:
        try:
            msg = json.loads(e.read()).get("error", str(e))
        except Exception:  # noqa: BLE001
            msg = str(e)
        raise OSError(f"HTTP {e.code}: {msg}") from None
    except HTTPException as e:
        raise OSError(f"truncated response: {e!r}") from None


class ShippedCorruptionError(RuntimeError):
    """A shipped WAL record failed its CRC / frame / sequence check on
    the follower: the batch is refused wholesale — applying a prefix
    would desync the replica from the seq stream."""


class Replication:
    """Per-process replication coordinator: role, term, peers.

    Attached to a StateServer (attach()); the server handler consults
    it for write gating (may_write), shipping (/wal), votes
    (/campaign) and status (/replication + /durability.replication).
    """

    def __init__(self, replica_id: str, peers: Optional[List[str]] = None,
                 self_url: str = "", replicate_from: str = "",
                 commit_quorum: int = 0, election_quorum: int = 0,
                 ttl: float = 3.0, sync_timeout: float = 10.0,
                 token: str = ""):
        self.replica_id = replica_id
        self.peers = [p.rstrip("/") for p in (peers or []) if p]
        self.self_url = self_url.rstrip("/")
        self.replicate_from = replicate_from
        group = len(self.peers) + 1
        majority = group // 2 + 1
        # commit quorum: replicas (leader included) that must hold a
        # record durably before its ack.  1 = async shipping (a lone
        # leader, or an explicit availability-over-durability choice).
        self.commit_quorum = int(commit_quorum) or majority
        # election quorum: votes (candidate included) to promote.  A
        # 2-node lab needs the explicit =1 override; the default
        # majority is the split-brain-safe setting for >=3.
        self.election_quorum = int(election_quorum) or majority
        self.ttl = float(ttl)
        self.sync_timeout = float(sync_timeout)
        self.token = token

        self.role = "follower" if replicate_from else "leader"
        # a follower may SERVE only once it has re-proven continuity
        # with the current group (first bootstrap / promotion): a
        # deposed leader rebooting over its old dir would otherwise
        # briefly serve its locally-recovered tail — records that
        # were never quorum-acked and that the re-sync is about to
        # discard (the chaos conductor caught exactly that sub-second
        # window as an rv regression)
        self.proven = self.role == "leader"
        self.term = 0
        # the term under which this replica's WAL SUFFIX was written
        # (Raft's lastLogTerm): elections compare (log_term, seq)
        # lexicographically — length alone would let a deposed
        # leader's LONGER but stale-term tail outvote a shorter
        # history that carries quorum-acked higher-term records
        self.log_term = 0
        self.voted_for = ""
        self.leader_url = replicate_from.rstrip("/") \
            if replicate_from and replicate_from != "auto" else ""
        self.state = None               # StateServer, set by attach()
        self.promotions = 0
        self.bootstraps = 0
        self.refused_batches = 0        # CRC/seq-refused shipped batches

        self._lock = threading.Lock()
        # leader side: follower ack tracking + the two conditions the
        # protocol waits on (new durable records to ship; quorum acks)
        self._ship_cv = threading.Condition(self._lock)
        self._quorum_cv = threading.Condition(self._lock)
        self._followers: Dict[str, dict] = {}
        # follower side: lag bookkeeping (monotonic clock)
        self._last_leader_ok = time.monotonic()
        self._caught_up_at = time.monotonic()
        self._caught_up = False
        self._stop = threading.Event()
        self._tail_thread: Optional[threading.Thread] = None
        # tail generation: bumped on every role transition so a tail
        # loop from a PREVIOUS follower stint (e.g. parked in a
        # long-poll across a promote->demote bounce) exits instead of
        # running concurrently with the fresh one — two tails would
        # double-apply shipped batches
        self._tail_gen = 0
        self._watchdog_thread: Optional[threading.Thread] = None
        # deterministic campaign jitter per replica (not wall-seeded:
        # two replicas must not campaign in lockstep)
        self._rng = random.Random(replica_id)

    # -- lifecycle -----------------------------------------------------

    def attach(self, state) -> None:
        self.state = state
        self._load_term()
        # the quorum FLOOR: everything recovered at boot (or held at
        # promotion) was already acked under a prior configuration's
        # quorum — it never needs re-acknowledgment by the current
        # follower set.  Only records appended past the floor gate
        # acks and watch visibility on the live quorum.
        self._quorum_floor_seq = state.durable.synced_seq
        self._quorum_floor_rv = state.durable.synced_rv
        if self.role == "leader":
            self.term = max(self.term, 1)
            self._persist_term()
        self._export_role()

    def start(self) -> None:
        """Spin the role threads (after the HTTP listener is up, so
        self_url answers peers)."""
        if self.role == "follower":
            self._start_tail()
        self._watchdog_thread = threading.Thread(
            target=self._watchdog, name="repl-watchdog", daemon=True)
        self._watchdog_thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            self._ship_cv.notify_all()
            self._quorum_cv.notify_all()

    def _start_tail(self) -> None:
        self._tail_thread = threading.Thread(
            target=self._tail_loop, name="repl-tail", daemon=True)
        self._tail_thread.start()

    # -- term persistence ----------------------------------------------

    def _term_path(self) -> str:
        return os.path.join(self.state.durable.dir, TERM_FILE)

    def _load_term(self) -> None:
        try:
            with open(self._term_path(), encoding="utf-8") as f:
                doc = json.load(f)
            self.term = int(doc.get("term", 0))
            self.log_term = int(doc.get("log_term", doc.get("term",
                                                            0)))
            self.voted_for = doc.get("voted_for", "")
        except (OSError, ValueError):
            # vtplint: disable=except-pass (first boot: no term file yet, term 0 is correct)
            pass

    def _persist_term(self) -> None:
        from volcano_tpu.server.durability import atomic_write_json
        atomic_write_json(self._term_path(),
                          {"term": self.term,
                           "log_term": self.log_term,
                           "voted_for": self.voted_for})

    # -- role / gating ---------------------------------------------------

    @property
    def is_leader(self) -> bool:
        return self.role == "leader"

    def may_write(self) -> bool:
        return self.role == "leader"

    def leader_hint(self) -> str:
        """Best-known leader URL for the 503 redirect hint."""
        if self.role == "leader":
            return self.self_url
        return self.leader_url

    def _export_role(self) -> None:
        from volcano_tpu import metrics
        metrics.swap_gauge_families(
            ("server_replication_role",),
            [("server_replication_role", {"role": r},
              1.0 if r == self.role else 0.0)
             for r in ("leader", "follower", "candidate")])
        metrics.set_gauge("server_replication_term", float(self.term))

    # -- leader: shipping + quorum ---------------------------------------

    def notify_durable(self) -> None:
        """Called by StateServer.commit() after the local fsync: wake
        follower long-polls parked in ship()."""
        with self._lock:
            self._ship_cv.notify_all()

    def ship(self, since_seq: int, follower: str, applied_seq: int,
             applied_rv: int, term: int, timeout: float) -> dict:
        """The /wal route: record the follower's durable position (its
        ack — this is what the commit quorum counts), then return the
        framed records past since_seq, long-polling for news."""
        from volcano_tpu import metrics
        st = self.state
        if self.role != "leader":
            return {"not_leader": True, "role": self.role,
                    "term": self.term, "leader": self.leader_hint()}
        if term > self.term:
            # a higher term exists: someone won an election we missed.
            # Refuse to ship at a stale term; the watchdog will demote.
            return {"not_leader": True, "role": self.role,
                    "term": self.term, "leader": ""}
        now = time.monotonic()
        with self._lock:
            self._followers[follower] = {
                "applied_seq": int(applied_seq),
                "applied_rv": int(applied_rv),
                "last_contact": now}
            self._quorum_cv.notify_all()
        deadline = time.monotonic() + max(0.0, min(timeout, 30.0))
        while True:
            out = st.durable.ship_since(since_seq, limit=SHIP_BATCH)
            if out["records"] or out["resync"] or \
                    time.monotonic() >= deadline or self._stop.is_set() \
                    or self.role != "leader":
                break
            with self._lock:
                self._ship_cv.wait(
                    min(0.5, max(0.01, deadline - time.monotonic())))
        if out["records"]:
            metrics.inc("server_replication_shipped_records_total",
                        value=float(len(out["records"])))
            metrics.inc("server_replication_shipped_bytes_total",
                        value=float(sum(len(r) for r in out["records"])))
            metrics.set_gauge("server_replication_last_shipped_rv",
                              float(st.durable.synced_rv))
        return {"term": self.term, "epoch": st.epoch,
                "leader": self.self_url or "",
                "last_seq": out["last_seq"],
                "snapshot_rv": st.durable.snapshot_rv,
                "resync": out["resync"], "records": out["records"]}

    def _evict_stale_followers_locked(self, now: float) -> None:
        """Drop tracking for followers silent past 10x the TTL: the
        map keys on the client-supplied follower id, so restarted
        replicas under new ids (or stray probes) would otherwise grow
        it — and its ids label a metric family — without bound."""
        horizon = now - 10 * self.ttl
        stale = [fid for fid, f in self._followers.items()
                 if f["last_contact"] < horizon]
        for fid in stale:
            del self._followers[fid]

    def quorum_positions(self) -> List[int]:
        """Durable seq positions across the group, leader first."""
        now = time.monotonic()
        horizon = now - 3 * self.ttl
        with self._lock:
            self._evict_stale_followers_locked(now)
            acks = [f["applied_seq"] for f in self._followers.values()
                    if f["last_contact"] >= horizon]
        return [self.state.durable.synced_seq] + sorted(acks,
                                                        reverse=True)

    def quorum_seq(self) -> int:
        """Highest seq held durably by a commit quorum of the group.
        RATCHETED via the floor: a position once quorum-held stays
        covered (those records were durable on a quorum at that
        instant — a follower later dying cannot un-happen them), so
        the horizon never regresses when an ack drops out of the
        contact window.  The floor starts at the boot/promotion
        horizon — the prefix acked under the prior configuration."""
        pos = self.quorum_positions()
        if self.commit_quorum <= 1:
            return pos[0]
        if len(pos) >= self.commit_quorum:
            with self._lock:
                self._quorum_floor_seq = max(
                    self._quorum_floor_seq,
                    pos[self.commit_quorum - 1])
        return self._quorum_floor_seq

    def quorum_rv(self) -> int:
        """The watch-visibility cap while leading a group: an event is
        released to mirrors only once a commit quorum could survive a
        leader loss still holding it."""
        if self.role != "leader" or self.commit_quorum <= 1:
            return self.state.durable.synced_rv
        horizon = time.monotonic() - 3 * self.ttl
        with self._lock:
            acks = [f["applied_rv"] for f in self._followers.values()
                    if f["last_contact"] >= horizon]
        pos = [self.state.durable.synced_rv] + sorted(acks,
                                                      reverse=True)
        if len(pos) >= self.commit_quorum:
            # same ratchet as quorum_seq: a revision once released
            # to mirrors must never disappear because the follower
            # that acked it died — its records WERE quorum-durable
            with self._lock:
                self._quorum_floor_rv = max(
                    self._quorum_floor_rv,
                    pos[self.commit_quorum - 1])
        return self._quorum_floor_rv

    def wait_quorum(self) -> None:
        """The replicated half of the ack barrier: block until a
        commit quorum holds the leader's current fsync horizon, or
        raise ReadOnlyError (-> 503 + Retry-After) on timeout.  The
        timeout IS the fence: a partitioned leader acks nothing."""
        from volcano_tpu.server.durability import ReadOnlyError
        if self.role != "leader" or self.commit_quorum <= 1:
            return
        target = self.state.durable.synced_seq
        deadline = time.monotonic() + self.sync_timeout
        while self.quorum_seq() < target:
            if self.role != "leader":
                raise ReadOnlyError("deposed mid-commit (replication "
                                    f"term {self.term})")
            remain = deadline - time.monotonic()
            if remain <= 0 or self._stop.is_set():
                raise ReadOnlyError(
                    f"replication quorum lost ({self.commit_quorum} "
                    f"needed, positions {self.quorum_positions()})")
            with self._lock:
                self._quorum_cv.wait(min(0.2, remain))

    def quorum_ok(self) -> bool:
        if self.role != "leader" or self.commit_quorum <= 1:
            return True
        return self.quorum_seq() >= self.state.durable.synced_seq

    # -- votes / promotion ------------------------------------------------

    def handle_campaign(self, body: dict) -> dict:
        """POST /campaign vote request.  Grant iff the candidate's
        term is news, its HISTORY is at least as current as ours —
        (log_term, seq) compared lexicographically, Raft's
        lastLogTerm rule: a deposed leader's longer stale-term tail
        must never outvote a shorter history carrying quorum-acked
        higher-term records — and WE also consider the leader dead
        (a follower in live contact refuses, so a partitioned
        minority cannot depose a healthy leader)."""
        term = int(body.get("term", 0))
        last_seq = int(body.get("last_seq", 0))
        log_term = int(body.get("log_term", 0))
        candidate = body.get("candidate", "")
        url = body.get("url", "")
        with self._lock:
            if self.role == "leader":
                # a live leader never votes; a candidate with a higher
                # term than a DEPOSED leader reaches it via watchdog
                return {"granted": False, "term": self.term,
                        "leader": self.self_url}
            silent = time.monotonic() - self._last_leader_ok
            my_seq = self.state.durable.synced_seq
            current = (log_term, last_seq) >= (self.log_term, my_seq)
            if term <= self.term or not current or \
                    silent < self.ttl:
                return {"granted": False, "term": self.term,
                        "reason": f"term={self.term} my_log="
                                  f"({self.log_term},{my_seq}) "
                                  f"leader_silent={silent:.2f}s"}
            self.term = term
            self.voted_for = candidate
            self._persist_term()
            if url:
                # optimistic re-target: if the candidate wins, the
                # next tail poll lands on the new leader immediately
                self.leader_url = url
        self._export_role()
        log.info("vote granted to %s at term %d", candidate, term)
        return {"granted": True, "term": term}

    def try_campaign(self) -> bool:
        """One election attempt at term+1.  Returns True on win."""
        new_term = self.term + 1
        my_seq = self.state.durable.synced_seq
        votes = 1                       # self
        body = {"term": new_term, "last_seq": my_seq,
                "log_term": self.log_term,
                "candidate": self.replica_id, "url": self.self_url}
        self.role = "candidate"
        self._export_role()
        log.info("campaigning at term %d (last_seq=%d, need %d votes)",
                 new_term, my_seq, self.election_quorum)
        for peer in self.peers:
            try:
                resp = http_json("POST", peer + "/campaign", body,
                                 timeout=max(1.0, self.ttl / 2),
                                 token=self.token)
            except (OSError, ValueError):
                # vtplint: disable=except-pass (an unreachable peer is a NO vote; the quorum count below is the signal)
                continue
            if resp.get("granted"):
                votes += 1
            elif int(resp.get("term", 0)) > new_term:
                # someone is already ahead: adopt and stand down
                self.term = int(resp["term"])
                self._persist_term()
                self.role = "follower"
                self._export_role()
                return False
        if votes >= self.election_quorum:
            return self.promote(new_term)
        self.role = "follower"
        self._export_role()
        log.info("election lost at term %d (%d/%d votes)", new_term,
                 votes, self.election_quorum)
        return False

    def promote(self, term: int) -> bool:
        """Become the leader at *term*: persist the term, bump the
        BOOT half of the epoch (same BASE — mirrors delta-resync
        across the promotion), open the write path, start shipping.

        ABANDONED (returns False) when this replica's term moved past
        *term* — OR when it granted ITS VOTE to another candidate at
        exactly *term* while its own campaign was in flight.  Two
        concurrent candidates otherwise both promote: the chaos
        conductor caught that dual-leader split twice — first on a
        higher-term grant, then on simultaneous same-term campaigns
        that cross-granted each other (both-abandon is safe; the
        per-replica campaign jitter breaks the ensuing retry tie)."""
        from volcano_tpu import metrics
        st = self.state
        with self._lock:
            if self.term > term or self.role == "leader" or \
                    (self.term == term and
                     self.voted_for not in ("", self.replica_id)):
                log.warning("promotion at term %d ABANDONED (term "
                            "now %d, role %s): a higher-term "
                            "candidate won mid-campaign", term,
                            self.term, self.role)
                abandoned = True
            else:
                abandoned = False
                self.term = term
                self.log_term = term    # our appends write at it
                self.voted_for = self.replica_id
                self.role = "leader"
                self._tail_gen += 1     # retire any parked tail loop
                self.proven = True
                self.leader_url = self.self_url
                self._followers.clear()
                self.promotions += 1
                # everything this replica holds was quorum-acked
                # under the old term (commit quorum included us); the
                # NEW follower set only gates what comes after
                self._quorum_floor_seq = st.durable.synced_seq
                self._quorum_floor_rv = st.durable.synced_rv
        if abandoned:
            if self.role != "leader":
                self.role = "follower"
            self._export_role()
            return False
        self._persist_term()
        st.on_promote()
        metrics.inc("server_replication_promotions_total")
        self._export_role()
        log.warning("PROMOTED to leader at term %d (epoch %s, rv %d, "
                    "seq %d)", term, st.epoch, st._rv,
                    st.durable.synced_seq)
        return True

    def demote(self, leader_url: str) -> None:
        """A deposed leader rejoining the group: flip to follower and
        let the tail loop full-resync (term mismatch forces the
        snapshot bootstrap)."""
        with self._lock:
            if self.role != "leader":
                return
            self.role = "follower"
            self.leader_url = leader_url
            self._tail_gen += 1     # the fresh tail owns this stint
            # our history diverged from the group's (that is WHY we
            # are demoting): serve nothing until the re-sync proves a
            # continuous prefix again
            self.proven = False
        self._last_leader_ok = time.monotonic()
        self._export_role()
        log.warning("DEPOSED: demoting to follower of %s (our term "
                    "%d was superseded)", leader_url, self.term)
        self._start_tail()

    def _watchdog(self) -> None:
        """Leader-side self-check, every ~ttl: probe the group for a
        higher term and demote on finding one.  Covers both the
        partition-heal path (our quorum moved on without us) and the
        idle deposed leader (no writes, so the quorum gate alone
        never trips — the chaos conductor caught exactly that replica
        sitting out a run as a stale 'leader')."""
        while not self._stop.wait(max(0.5, self.ttl)):
            if self.role != "leader" or not self.peers:
                continue
            for peer in self.peers:
                try:
                    doc = http_json("GET", peer + "/replication",
                                    timeout=2.0, token=self.token)
                except (OSError, ValueError):
                    # vtplint: disable=except-pass (watchdog probe: a dark peer proves nothing, the next tick re-probes)
                    continue
                if int(doc.get("term", 0)) > self.term:
                    hint = doc.get("leader") or (
                        peer if doc.get("role") == "leader" else "")
                    if hint and hint.rstrip("/") != self.self_url:
                        self.demote(hint)
                        break

    # -- follower: bootstrap + tail ---------------------------------------

    def _discover_leader(self) -> str:
        """Scan the peer group for the current leader (highest term
        wins); used by --replicate-from auto and after a lost leader."""
        best, best_term = "", -1
        for peer in self.peers:
            try:
                doc = http_json("GET", peer + "/replication",
                                timeout=2.0, token=self.token)
            except (OSError, ValueError):
                # vtplint: disable=except-pass (discovery scan: a dark peer simply cannot be the leader we adopt)
                continue
            term = int(doc.get("term", 0))
            if doc.get("role") == "leader" and term > best_term:
                best, best_term = peer, term
            elif doc.get("leader") and term > best_term:
                best, best_term = doc["leader"], term
        return best

    def _bootstrap(self, leader: str) -> None:
        """Full re-sync: install the leader's replica snapshot (store
        + leases + req cache + wal_seq + term) over the local state —
        the path a follower behind the ship ring, a fresh dir, or an
        epoch/term mismatch all take."""
        from volcano_tpu import metrics
        doc = http_json("GET", leader + "/replica_snapshot",
                        timeout=60.0, token=self.token)
        self.state.install_replica_snapshot(doc)
        new_term = int(doc.get("term", 0))
        if new_term > self.term:
            self.term = new_term
            self.voted_for = ""
        # the installed history IS the leader's: its suffix term too
        self.log_term = new_term or self.log_term
        self._persist_term()
        self.bootstraps += 1
        metrics.inc("server_replication_bootstraps_total")
        with self._lock:
            # a bootstrap installs the leader's full state: the
            # replica is provably current at this instant — and
            # provably CONTINUOUS with the group, so it may serve
            self._caught_up = True
            self._caught_up_at = time.monotonic()
            self.proven = True
        self._export_role()
        log.info("bootstrapped from %s: rv=%d seq=%d term=%d epoch=%s",
                 leader, self.state._rv, self.state.durable.synced_seq,
                 self.term, self.state.epoch)

    def _mark_behind(self) -> None:
        """The follower can no longer prove it is current (failed
        poll, partition, stale-leader answer): advertised lag starts
        counting from the LAST successful leader contact — never a
        frozen 0 (the bounded-staleness invariant audits exactly
        this)."""
        with self._lock:
            if self._caught_up:
                self._caught_up = False
                self._caught_up_at = self._last_leader_ok

    def lag_seconds(self) -> float:
        with self._lock:
            if self.role == "leader":
                return 0.0
            # a dead tail thread can never claim currency: whatever
            # killed it, the replica stopped applying — advertise the
            # drift from the last proven contact (defense in depth on
            # top of the tail loop's own exception normalization)
            tail_dead = (self._tail_thread is not None
                         and not self._tail_thread.is_alive()
                         and not self._stop.is_set())
            if self._caught_up and not tail_dead:
                return 0.0
            ref = self._last_leader_ok if tail_dead and \
                self._caught_up else self._caught_up_at
            return time.monotonic() - ref

    def _tail_loop(self) -> None:
        from volcano_tpu import metrics
        from volcano_tpu.server.durability import ReadOnlyError
        st = self.state
        gen = self._tail_gen
        backoff = 0.1
        bootstrapped_term = None
        while not self._stop.is_set() and self.role == "follower" \
                and self._tail_gen == gen:
            leader = self.leader_url
            if not leader:
                leader = self._discover_leader()
                if not leader:
                    if self._stop.wait(min(backoff, 1.0)):
                        return
                    backoff = min(backoff * 2, 2.0)
                    self._maybe_campaign()
                    continue
                self.leader_url = leader
            try:
                resp = http_json(
                    "GET",
                    f"{leader}/wal?since_seq={st.durable.synced_seq}"
                    f"&follower={self.replica_id}"
                    f"&applied_seq={st.durable.synced_seq}"
                    f"&applied_rv={st.durable.synced_rv}"
                    f"&term={self.term}&timeout={WAL_POLL_S}",
                    timeout=WAL_POLL_S + 3.0, token=self.token)
            except (OSError, ValueError) as e:
                log.debug("wal poll to %s failed (%s)", leader, e)
                self._mark_behind()
                from volcano_tpu import metrics
                metrics.set_gauge("server_replication_lag_seconds",
                                  self.lag_seconds())
                if self._stop.wait(min(backoff, 1.0)):
                    return
                backoff = min(backoff * 2, 2.0)
                self._maybe_campaign()
                continue
            backoff = 0.1
            if self.role != "follower" or self._tail_gen != gen:
                # promoted/demoted (or stopped) while this poll was
                # in flight: a retired tail must NOT apply records —
                # the new role (or the fresh tail) owns the history
                return
            if resp.get("not_leader"):
                self._mark_behind()
                r_term = int(resp.get("term", 0) or 0)
                if resp.get("role") == "leader" and r_term and \
                        r_term < self.term and \
                        time.monotonic() - self._last_leader_ok \
                        > 3 * self.ttl:
                    # liveness valve: we granted/advanced a term that
                    # never produced a leader (failed election), and
                    # the only live leader refuses our inflated term.
                    # Far past any in-flight promotion window, step
                    # back down to its term and tail it.
                    log.warning("adopting the live leader's term %d "
                                "(our term %d produced no leader)",
                                r_term, self.term)
                    self.term = r_term
                    self.voted_for = ""
                    self._persist_term()
                    continue
                hinted = (resp.get("leader") or "").rstrip("/")
                self.leader_url = hinted if hinted != self.self_url \
                    else ""
                self._maybe_campaign()
                continue
            term = int(resp.get("term", 0))
            if term < self.term:
                # stale leader from a superseded term: never apply
                self.leader_url = ""
                continue
            self._last_leader_ok = time.monotonic()
            needs_boot = (
                resp.get("resync")
                or term > self.term
                or bootstrapped_term != term
                or self._epoch_base(resp.get("epoch", "")) !=
                self._epoch_base(st.epoch))
            if needs_boot and (resp.get("resync") or
                               bootstrapped_term is None or
                               term != bootstrapped_term):
                # epoch/term mismatch or ship-ring fall-off: the tail
                # cannot prove continuity — full re-sync
                self._mark_behind()
                try:
                    self._bootstrap(leader)
                    bootstrapped_term = self.term
                except (OSError, ValueError) as e:
                    log.warning("bootstrap from %s failed (%s)",
                                leader, e)
                    if self._stop.wait(0.5):
                        return
                continue
            records = resp.get("records") or []
            if records and term != self.log_term:
                # the suffix we are about to journal was written at
                # the leader's term: record it BEFORE applying (the
                # election currency comparison reads it)
                self.log_term = term
                self._persist_term()
            if records:
                try:
                    st.apply_shipped(records)
                except ShippedCorruptionError as e:
                    # in-flight corruption: refuse the whole batch and
                    # re-request — NEVER a partial apply
                    self.refused_batches += 1
                    metrics.inc(
                        "server_replication_refused_batches_total")
                    log.error("shipped batch REFUSED (%s); "
                              "re-requesting from seq %d", e,
                              st.durable.synced_seq)
                    if self._stop.wait(0.1):
                        return
                    continue
                except ReadOnlyError as e:
                    # THIS replica's own disk degraded mid-apply:
                    # wait out the store's heal loop, then force a
                    # full re-sync — the heal writes a probe record
                    # into the local WAL, so the local seq stream
                    # has diverged from the leader's and a tail can
                    # never safely continue.  The thread must
                    # survive this (a dead tail never recovers and
                    # never campaigns).
                    self._mark_behind()
                    log.error("follower store degraded mid-apply "
                              "(%s); waiting for heal, then "
                              "re-syncing", e)
                    while not self._stop.is_set() and \
                            self._tail_gen == gen and \
                            st.readonly_reason:
                        if self._stop.wait(0.5):
                            return
                    bootstrapped_term = None    # force bootstrap
                    continue
            caught = st.durable.synced_seq >= int(
                resp.get("last_seq", 0))
            with self._lock:
                if caught:
                    self._caught_up = True
                    self._caught_up_at = time.monotonic()
                elif self._caught_up:
                    self._caught_up = False
                    self._caught_up_at = time.monotonic()
            metrics.set_gauge("server_replication_lag_seconds",
                              self.lag_seconds())
            metrics.set_gauge("server_replication_applied_rv",
                              float(st.durable.synced_rv))

    def _maybe_campaign(self) -> None:
        """Campaign when the leader has been silent past the TTL plus
        a per-replica jitter slot (staggers simultaneous candidates)."""
        if self.role != "follower" or self._stop.is_set():
            return
        silent = time.monotonic() - self._last_leader_ok
        if silent < self.ttl + self._rng.uniform(0.0, self.ttl / 2):
            return
        if self.try_campaign():
            return
        # lost or yielded: wait a beat so the winner can reach us
        self._last_leader_ok = time.monotonic() - self.ttl / 2

    @staticmethod
    def _epoch_base(epoch: str) -> str:
        return epoch.rsplit(".", 1)[0]

    # -- status ----------------------------------------------------------

    def status(self) -> dict:
        from volcano_tpu import metrics
        st = self.state
        now = time.monotonic()
        out = {
            "replica_id": self.replica_id,
            "role": self.role,
            "proven": self.proven,
            "term": self.term,
            "leader": self.leader_hint(),
            "peers": self.peers,
            "commit_quorum": self.commit_quorum,
            "applied_seq": st.durable.synced_seq,
            "applied_rv": st.durable.synced_rv,
            "lag_s": round(self.lag_seconds(), 3),
            "promotions": self.promotions,
            "bootstraps": self.bootstraps,
            "refused_batches": self.refused_batches,
        }
        if self.role == "leader":
            with self._lock:
                self._evict_stale_followers_locked(now)
                out["followers"] = {
                    fid: {"applied_seq": f["applied_seq"],
                          "applied_rv": f["applied_rv"],
                          # seconds since the follower's last ack —
                          # bounded by the long-poll period on an
                          # idle group, so it measures CONTACT, not
                          # staleness (the follower's own lag_s does)
                          "ack_age_s": round(max(
                              0.0, now - f["last_contact"]), 3)}
                    for fid, f in self._followers.items()}
            out["last_shipped_rv"] = st.durable.synced_rv
            out["quorum_ok"] = self.quorum_ok()
            # whole-family swap: a departed follower's series drops
            # out instead of lingering as a stale labeled gauge
            metrics.swap_gauge_families(
                ("server_replication_follower_lag_rv",),
                [("server_replication_follower_lag_rv",
                  {"follower": fid},
                  float(st.durable.synced_rv - f["applied_rv"]))
                 for fid, f in out["followers"].items()])
        metrics.set_gauge("server_replication_lag_seconds",
                          out["lag_s"])
        return out
