"""Shared HTTP plumbing for the control-plane servers (state server,
webhook manager): JSON responses with broken-pipe tolerance and the
bound-handler + threaded-server bootstrap."""

from __future__ import annotations

import json
import threading
from http.server import ThreadingHTTPServer


def json_response(handler, code: int, payload) -> None:
    """Write a JSON response; a client that went away mid-response
    (killed scheduler, cancelled watch) is routine, not an error."""
    body = json.dumps(payload, separators=(",", ":")).encode()
    try:
        handler.send_response(code)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)
    except (BrokenPipeError, ConnectionResetError):
        handler.close_connection = True


def serve_threaded(handler_base: type, attrs: dict, port: int,
                   name: str) -> ThreadingHTTPServer:
    """Bind per-server state onto a handler subclass and serve it on
    127.0.0.1:port (0 = ephemeral) from a daemon thread."""
    handler = type("BoundHandler", (handler_base,), attrs)
    httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
    httpd.daemon_threads = True
    threading.Thread(target=httpd.serve_forever, name=name,
                     daemon=True).start()
    return httpd
