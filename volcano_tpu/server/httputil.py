"""Shared HTTP plumbing for the control-plane servers (state server,
webhook manager): JSON responses with broken-pipe tolerance and the
bound-handler + threaded-server bootstrap."""

from __future__ import annotations

import gzip
import json
import threading
from http.server import ThreadingHTTPServer

# bodies below this stay plain: gzip's ~20-byte header + the deflate
# call cost more than the wire bytes they save on small control
# responses ({"ok":true} and friends)
GZIP_MIN_BYTES = 512


def read_json_body(resp):
    """Client half of the encoding negotiation: read an http.client
    response and parse JSON, inflating a gzip'd body.  Lives beside
    the compression half (json_response) so the two can never drift —
    every wire client (RemoteCluster, AuditExporter) reads through
    here."""
    body = resp.read()
    if resp.headers.get("Content-Encoding") == "gzip":
        body = gzip.decompress(body)
    return json.loads(body)


def json_response(handler, code: int, payload, headers=None,
                  trickle_ms: float = 0.0) -> None:
    """Write a JSON response; a client that went away mid-response
    (killed scheduler, cancelled watch) is routine, not an error.

    headers: extra response headers (e.g. Retry-After on the read-only
    degrade 503s).  trickle_ms > 0 is the injected slow-loris fault:
    the body dribbles out in tiny chunks with that gap between them —
    a complete but pathologically slow response, the gray-failure
    shape timeouts exist for.

    Large SUCCESS bodies are gzip-compressed when the client
    advertised `Accept-Encoding: gzip` — snapshot/watch payloads are
    the wire fast lane's dominant byte cost and JSON object dumps
    deflate 5-10x.  Level 1: the hot bodies are codec output
    (repetitive tag strings), where higher levels buy little but cost
    CPU.  Error bodies stay plain regardless: urllib surfaces them
    through HTTPError.read(), which every client parses raw for the
    diagnostic message — a gzip'd 422 would turn an admission veto's
    reason into mojibake exactly when the operator needs it."""
    body = json.dumps(payload, separators=(",", ":")).encode()
    encoding = ""
    if code < 400 and len(body) >= GZIP_MIN_BYTES and "gzip" in (
            handler.headers.get("Accept-Encoding") or ""):
        body = gzip.compress(body, compresslevel=1)
        encoding = "gzip"
    try:
        handler.send_response(code)
        handler.send_header("Content-Type", "application/json")
        if encoding:
            handler.send_header("Content-Encoding", encoding)
        handler.send_header("Content-Length", str(len(body)))
        for name, value in (headers or {}).items():
            handler.send_header(name, str(value))
        handler.end_headers()
        if trickle_ms > 0:
            import time as _time
            # first ~1KB in 64-byte sips, the rest in one gulp: slow
            # enough to exercise client timeouts, bounded enough that
            # a patient client still completes
            for i in range(0, min(len(body), 1024), 64):
                handler.wfile.write(body[i:i + 64])
                handler.wfile.flush()
                _time.sleep(trickle_ms / 1000.0)
            handler.wfile.write(body[min(len(body), 1024):])
        else:
            handler.wfile.write(body)
    except (BrokenPipeError, ConnectionResetError):
        handler.close_connection = True


def serve_threaded(handler_base: type, attrs: dict, port: int,
                   name: str, tls_cert: str = "",
                   tls_key: str = "") -> ThreadingHTTPServer:
    """Bind per-server state onto a handler subclass and serve it on
    127.0.0.1:port (0 = ephemeral) from a daemon thread.  With
    tls_cert/tls_key the listener speaks TLS only — a plaintext client
    is refused during the handshake (reference: the webhook manager is
    TLS-only, cmd/webhook-manager/)."""
    # per-connection timeout (handler.setup applies it to the socket):
    # a silent peer must pin at most one worker thread, and must be
    # longer than the /watch long-poll ceiling (55s)
    attrs = dict(attrs, timeout=65)
    handler = type("BoundHandler", (handler_base,), attrs)
    httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
    if tls_cert:
        from volcano_tpu.server.tlsutil import server_ssl_context
        # handshake lazily on first read IN THE WORKER THREAD — with
        # do_handshake_on_connect a stalled client would block the
        # single accept loop and take down the whole listener
        httpd.socket = server_ssl_context(tls_cert, tls_key).wrap_socket(
            httpd.socket, server_side=True,
            do_handshake_on_connect=False)
    httpd.daemon_threads = True
    threading.Thread(target=httpd.serve_forever, name=name,
                     daemon=True).start()
    return httpd
