"""State server: the apiserver analogue for multi-process deployments."""

from volcano_tpu.server.state_server import StateServer, serve

__all__ = ["StateServer", "serve"]
