"""Run the state server standalone.

    python -m volcano_tpu.server --port 8700 --data-dir ./state \
        --tick-period 1.0

--data-dir enables the crash-safe layer (WAL + snapshots, fsync
before every ack; server/durability.py): a kill -9 loses nothing that
was acked, and the next boot replays snapshot-then-WAL and resumes
the event counter monotonically.  --state remains as the legacy
single-file mode: it loads EITHER the old pickle or the snapshot-JSON
format, and the graceful save is routed through the same atomic
snapshot writer (but a hard kill still loses everything since the
last save — use --data-dir for durability).
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys
import threading


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="volcano-tpu-server")
    parser.add_argument("--port", type=int, default=8700)
    parser.add_argument("--data-dir", default="",
                        help="durable state directory (WAL + "
                             "snapshots, fsync before ack); survives "
                             "kill -9")
    parser.add_argument("--state", default="",
                        help="legacy single-file state to load/save "
                             "on graceful shutdown (pickle or "
                             "snapshot JSON — both load); when "
                             "--data-dir already holds state, that "
                             "wins and --state only receives the "
                             "shutdown export")
    parser.add_argument("--tick-period", type=float, default=0.0,
                        help="self-tick the simulated kubelet every N "
                             "seconds (0 = external /tick only)")
    parser.add_argument("--webhook-url", default="",
                        help="external webhook-manager to call for "
                             "admission instead of the embedded chain "
                             "(vc-webhook-manager analogue)")
    parser.add_argument("--webhook-failure-policy",
                        choices=["Fail", "Ignore"], default="Fail")
    parser.add_argument("--tls-cert", default="",
                        help="serve TLS with this certificate (PEM); "
                             "plaintext clients are refused")
    parser.add_argument("--tls-key", default="")
    parser.add_argument("--gen-tls", action="store_true",
                        help="generate a self-signed cert/key at the "
                             "--tls-cert/--tls-key paths first")
    parser.add_argument("--token", default="",
                        help="cluster bearer token: required on every "
                             "route except /healthz and /metrics "
                             "(also presented on webhook callouts)")
    parser.add_argument("--token-file", default="")
    parser.add_argument("--fault-plan", default="",
                        help="ARM THE CHAOS ENGINE: a faults.FaultPlan "
                             "JSON doc (inline, or @/path/to/plan.json)"
                             " injecting wire/disk/clock faults in "
                             "this process; also read from the "
                             "VTP_FAULT_PLAN env var.  Never use in "
                             "production")
    parser.add_argument("--replica-id", default="",
                        help="join a replica group under this id "
                             "(requires --data-dir); without "
                             "--replicate-from this process is the "
                             "seed leader")
    parser.add_argument("--peers", default="",
                        help="comma-separated URLs of the OTHER "
                             "replicas (quorums are majorities of "
                             "the full group, this replica included)")
    parser.add_argument("--replicate-from", default="",
                        help="start as a FOLLOWER of this leader URL "
                             "('auto' discovers the leader among "
                             "--peers): ship + replay its WAL, serve "
                             "reads at advertised staleness, refuse "
                             "writes with a leader hint, campaign on "
                             "leader death")
    parser.add_argument("--commit-quorum", type=int, default=0,
                        help="replicas (leader included) that must "
                             "hold a write durably before its ack "
                             "(default: group majority; 1 = async "
                             "shipping)")
    parser.add_argument("--election-quorum", type=int, default=0,
                        help="votes (candidate included) needed to "
                             "promote (default: group majority; a "
                             "2-node lab needs the explicit 1 — see "
                             "docs/design/replication.md on split "
                             "brain)")
    parser.add_argument("--repl-ttl", type=float, default=3.0,
                        help="leader-silence window before followers "
                             "campaign")
    parser.add_argument("--wal-force-truncate", action="store_true",
                        help="explicit operator override for mid-WAL "
                             "corruption: truncate the log at the "
                             "corrupt record and ACCEPT THE DATA LOSS "
                             "instead of refusing to boot")
    parser.add_argument("--webhook-ca-cert", default="",
                        help="CA bundle for --webhook-url callouts")
    parser.add_argument("--webhook-insecure", action="store_true")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s")
    log = logging.getLogger("volcano_tpu.server")

    from volcano_tpu.cache.fake_cluster import FakeCluster
    from volcano_tpu.server.state_server import serve
    from volcano_tpu.server.tlsutil import generate_self_signed, load_token
    from volcano_tpu.webhooks import default_admission

    token = load_token(args.token, args.token_file)
    if args.gen_tls:
        if not (args.tls_cert and args.tls_key):
            parser.error("--gen-tls needs --tls-cert and --tls-key paths")
        generate_self_signed(args.tls_cert, args.tls_key)
        log.info("self-signed TLS material written to %s / %s",
                 args.tls_cert, args.tls_key)

    from volcano_tpu import faults as faults_mod
    from volcano_tpu.server.durability import (DurableStore,
                                               WALCorruptionError,
                                               atomic_write_json,
                                               load_cluster_file)
    plan = None
    if args.fault_plan:
        raw = args.fault_plan
        if raw.startswith("@"):
            with open(raw[1:], encoding="utf-8") as f:
                raw = f.read()
        import json as _json
        plan = faults_mod.FaultPlan.from_doc(_json.loads(raw))
        log.warning("fault plan ACTIVE (seed=%d, %d rules)",
                    plan.seed, len(plan.rules))
    else:
        plan = faults_mod.FaultPlan.from_env()
    if plan is not None:
        faults_mod.install_clock_faults(plan)

    durable = None
    cluster = None
    if args.data_dir:
        vfs = None
        if plan is not None and any(r.site == "disk"
                                    for r in plan.rules):
            vfs = faults_mod.FaultyVFS(plan)
        durable = DurableStore(args.data_dir, vfs=vfs,
                               force_truncate=args.wal_force_truncate)
        try:
            rec = durable.recover()
        except WALCorruptionError as e:
            # REFUSE TO START: replaying past mid-WAL corruption
            # silently drops every later acked write.  The operator
            # restores the segment or accepts the loss explicitly.
            log.critical("%s", e)
            return 3
        cluster = rec.cluster
        if cluster is not None:
            log.info("recovered durable state from %s (%d nodes, %d "
                     "pods, rv %d, %d WAL records replayed in %.3fs, "
                     "epoch %s)", args.data_dir, len(cluster.nodes),
                     len(cluster.pods), rec.rv, rec.replay_records,
                     rec.replay_seconds, rec.epoch)
    if cluster is None and args.state and os.path.exists(args.state):
        # legacy alias: sniffs pickle vs snapshot JSON.  With an empty
        # --data-dir this seeds the durable store (the initial
        # snapshot lands before the first ack).
        cluster = load_cluster_file(args.state)
        if cluster.admission is None:
            cluster.admission = default_admission()
        log.info("loaded state from %s (%d nodes, %d pods)",
                 args.state, len(cluster.nodes), len(cluster.pods))
    elif args.state and os.path.exists(args.state) and \
            cluster is not None:
        log.info("durable state in %s takes precedence; %s will only "
                 "receive the shutdown export", args.data_dir,
                 args.state)

    from volcano_tpu.webhooks.server import RemoteAdmission
    if args.webhook_url:
        if cluster is None:
            cluster = FakeCluster()
        cluster.admission = RemoteAdmission(
            args.webhook_url,
            failure_policy=args.webhook_failure_policy,
            token=token, ca_cert=args.webhook_ca_cert,
            insecure=args.webhook_insecure)
        log.info("admission delegated to webhook manager at %s "
                 "(failurePolicy=%s)", args.webhook_url,
                 args.webhook_failure_policy)
    elif cluster is not None and \
            isinstance(cluster.admission, RemoteAdmission):
        # a RemoteAdmission pickled into the state file must not
        # outlive the flag: restarting without --webhook-url means
        # embedded admission, not a (likely dead) webhook endpoint
        log.info("state file carried a webhook admission proxy; "
                 "reverting to the embedded chain (no --webhook-url)")
        cluster.admission = default_admission()

    replication = None
    if args.replica_id or args.replicate_from:
        if durable is None:
            parser.error("replication requires --data-dir (followers "
                         "journal the shipped WAL before serving it)")
        from volcano_tpu.server.replication import Replication
        replication = Replication(
            replica_id=args.replica_id or f"replica-{args.port}",
            peers=[p for p in args.peers.split(",") if p],
            replicate_from=args.replicate_from,
            commit_quorum=args.commit_quorum,
            election_quorum=args.election_quorum,
            ttl=args.repl_ttl, token=token)

    httpd, state = serve(port=args.port, cluster=cluster,
                         tick_period=args.tick_period,
                         tls_cert=args.tls_cert, tls_key=args.tls_key,
                         token=token, durable=durable, faults=plan,
                         replication=replication)
    if replication is not None:
        log.info("replication: id=%s role=%s term=%d peers=%s "
                 "commit-quorum=%d election-quorum=%d",
                 replication.replica_id, replication.role,
                 replication.term, replication.peers,
                 replication.commit_quorum,
                 replication.election_quorum)
    log.info("state server on %s://127.0.0.1:%d%s%s",
             "https" if args.tls_cert else "http",
             httpd.server_address[1],
             " (bearer auth on writes)" if token else "",
             f" [durable: {args.data_dir}]" if durable else "")

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()

    state.tick_stop.set()   # no kubelet mutations during save
    if replication is not None:
        replication.stop()
    httpd.shutdown()
    if durable is not None:
        if durable.poisoned:
            log.error("shutting down READ-ONLY (%s): skipping the "
                      "final compaction — the last durable snapshot + "
                      "WAL prefix is the recovery point",
                      durable.poisoned)
        else:
            # final compaction so the next boot replays zero WAL
            state.write_snapshot()
            log.info("durable state compacted in %s", args.data_dir)
        durable.close()
    if args.state:
        # the graceful save routes through the same snapshot capture +
        # atomic writer the WAL compactor uses: the store/event locks
        # make the capture consistent even if a straggling handler
        # thread is still mutating (the old direct pickle raced them),
        # and write-temp + rename means a crash mid-save never tears
        # the last good file
        atomic_write_json(args.state, state.disk_snapshot_doc())
        log.info("state saved to %s (snapshot format)", args.state)
    return 0


if __name__ == "__main__":
    sys.exit(main())
