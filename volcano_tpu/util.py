"""Shared utilities: comparator-based priority queues, rate windows.

Reference parity: pkg/scheduler/util/priority_queue.go.
"""

from __future__ import annotations

import heapq
import itertools
from typing import Callable, Generic, Iterable, List, Optional, TypeVar

T = TypeVar("T")


class RateWindow:
    """Windowed EWMA rate over a monotonically-increasing counter —
    the one copy of the counter-delta machinery shared by the agent's
    collectors (NetAccountingCollector byte counters, GoodputCollector
    step counters).

    Semantics per fold(reading, ts):

      * a None reading leaves the window untouched: the direction
        simply spans to the next successful read (a one-sided failed
        read must not tear the other counter's window);
      * the first reading opens the window — no rate yet;
      * a reading >= the last one is a delta over dt, folded into the
        EWMA (the very first window seeds the EWMA directly);
      * a reading BELOW the last one is a counter reset, interpreted
        per *reset* policy:
          - "absolute" (byte counters): the exporter restarted; the
            new absolute value IS the delta (the bytes since the
            reset — the only defensible reading);
          - "restart"  (step counters): the SOURCE restarted (a
            drained worker resuming from a checkpoint floor) — the
            window restarts with NO delta, because crediting the
            resumed absolute step count as progress would inflate the
            rate, and a negative delta is meaningless.  The EWMA is
            retained and decays into the new windows.

    restart() forces the "restart" handling explicitly — callers with
    an out-of-band restart signal (a resize-epoch bump) call it even
    when the counter happens to land higher than the last reading.
    """

    __slots__ = ("alpha", "reset", "scale", "last", "last_ts", "rate")

    def __init__(self, alpha: float = 0.5, reset: str = "absolute",
                 scale: float = 1.0):
        if reset not in ("absolute", "restart"):
            raise ValueError(f"unknown reset policy {reset!r}")
        self.alpha = float(alpha)
        self.reset = reset
        self.scale = float(scale)       # e.g. bytes -> mbps: 8/1e6
        self.last: Optional[float] = None
        self.last_ts: Optional[float] = None
        self.rate = 0.0                 # windowed EWMA, scaled units

    def restart(self) -> None:
        """Drop the window (source restarted); the EWMA survives."""
        self.last = None
        self.last_ts = None

    def fold(self, cur: Optional[float], ts: float) -> float:
        """Fold one reading; returns the (possibly unchanged) rate."""
        if cur is None:
            return self.rate
        if self.last is None:           # first reading: no window yet
            self.last, self.last_ts = cur, ts
            return self.rate
        if cur >= self.last:
            delta = cur - self.last
        elif self.reset == "absolute":
            delta = cur                 # exporter reset: cur IS delta
        else:                           # "restart": re-open, no delta
            self.last, self.last_ts = cur, ts
            return self.rate
        dt = ts - self.last_ts if self.last_ts is not None else 0.0
        self.last, self.last_ts = cur, ts
        if dt > 0:
            inst = delta * self.scale / dt
            self.rate = inst if self.rate == 0.0 else \
                self.alpha * inst + (1 - self.alpha) * self.rate
        return self.rate


class PriorityQueue(Generic[T]):
    """Heap ordered by a less(a, b) comparator (True => a pops first).

    Insertion order breaks ties so scheduling is deterministic.
    """

    def __init__(self, less: Callable[[T, T], bool],
                 items: Iterable[T] = ()):
        self._less = less
        self._counter = itertools.count()
        self._heap: List["_Entry[T]"] = []
        for it in items:
            self.push(it)

    def push(self, item: T):
        heapq.heappush(self._heap, _Entry(item, next(self._counter), self._less))

    def pop(self) -> T:
        return heapq.heappop(self._heap).item

    def peek(self) -> T:
        return self._heap[0].item

    def empty(self) -> bool:
        return not self._heap

    def __len__(self) -> int:
        return len(self._heap)

    def __iter__(self):
        """Drain-iterate in priority order (consumes the queue)."""
        while not self.empty():
            yield self.pop()


class _Entry(Generic[T]):
    __slots__ = ("item", "seq", "less")

    def __init__(self, item: T, seq: int, less: Callable[[T, T], bool]):
        self.item = item
        self.seq = seq
        self.less = less

    def __lt__(self, other: "_Entry[T]") -> bool:
        if self.less(self.item, other.item):
            return True
        if self.less(other.item, self.item):
            return False
        return self.seq < other.seq


