"""Shared utilities: comparator-based priority queues, helpers.

Reference parity: pkg/scheduler/util/priority_queue.go.
"""

from __future__ import annotations

import heapq
import itertools
from typing import Callable, Generic, Iterable, List, Optional, TypeVar

T = TypeVar("T")


class PriorityQueue(Generic[T]):
    """Heap ordered by a less(a, b) comparator (True => a pops first).

    Insertion order breaks ties so scheduling is deterministic.
    """

    def __init__(self, less: Callable[[T, T], bool],
                 items: Iterable[T] = ()):
        self._less = less
        self._counter = itertools.count()
        self._heap: List["_Entry[T]"] = []
        for it in items:
            self.push(it)

    def push(self, item: T):
        heapq.heappush(self._heap, _Entry(item, next(self._counter), self._less))

    def pop(self) -> T:
        return heapq.heappop(self._heap).item

    def peek(self) -> T:
        return self._heap[0].item

    def empty(self) -> bool:
        return not self._heap

    def __len__(self) -> int:
        return len(self._heap)

    def __iter__(self):
        """Drain-iterate in priority order (consumes the queue)."""
        while not self.empty():
            yield self.pop()


class _Entry(Generic[T]):
    __slots__ = ("item", "seq", "less")

    def __init__(self, item: T, seq: int, less: Callable[[T, T], bool]):
        self.item = item
        self.seq = seq
        self.less = less

    def __lt__(self, other: "_Entry[T]") -> bool:
        if self.less(self.item, other.item):
            return True
        if self.less(other.item, self.item):
            return False
        return self.seq < other.seq


