"""Simulated TPU cluster provisioning — the KWOK-analogue harness.

Reference parity: benchmark/scripts/create-kwok-nodes.sh +
create-hypernodes.sh (fake nodes + synthetic rack/spine topologies).
Here fake nodes are fake TPU slice hosts: correct GKE-style labels,
chips-per-host allocatable, worker ids and ICI coordinates, grouped
into DCN pods — so gang + topology scheduling is exercised at
hundreds-of-hosts scale with zero real machines (SURVEY.md §4.4).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from volcano_tpu.api.node_info import Node
from volcano_tpu.api.resource import TPU
from volcano_tpu.api.types import (
    TPU_COORDS_LABEL,
    TPU_SLICE_LABEL,
    TPU_TOPOLOGY_LABEL,
    TPU_WORKER_ID_LABEL,
)
from volcano_tpu.api.devices.tpu.topology import SliceTopology, slice_for
from volcano_tpu.cache.fake_cluster import FakeCluster
from volcano_tpu.controllers.hypernode import DCN_POD_LABEL

ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"


def slice_nodes(slice_topo: SliceTopology, dcn_pod: str = "",
                cpu_per_host: int = 112, mem_gi: int = 192) -> List[Node]:
    """Materialize one slice as its host nodes with full TPU labels."""
    from volcano_tpu.api.goodput import GENERATION_LABEL, generation_of
    nodes = []
    for worker in range(slice_topo.num_hosts):
        coords = slice_topo.host_coords(worker)
        labels = {
            TPU_SLICE_LABEL: slice_topo.name,
            TPU_TOPOLOGY_LABEL: "x".join(str(d) for d in slice_topo.topology),
            TPU_WORKER_ID_LABEL: str(worker),
            TPU_COORDS_LABEL: ",".join(str(c) for c in coords),
            ACCELERATOR_LABEL: slice_topo.accelerator,
        }
        # hardware generation attribute (api/goodput.py): the key the
        # throughput-vector estimator and frag gauges group by
        labels[GENERATION_LABEL] = generation_of(labels)
        if dcn_pod:
            labels[DCN_POD_LABEL] = dcn_pod
        nodes.append(Node(
            name=f"{slice_topo.name}-w{worker}",
            labels=labels,
            allocatable={"cpu": cpu_per_host, "memory": f"{mem_gi}Gi",
                         TPU: slice_topo.chips_per_host, "pods": 110},
        ))
    return nodes


def make_tpu_cluster(
        slices: Sequence[Tuple[str, str]],
        dcn_pods: Optional[Dict[str, str]] = None,
        extra_nodes: Sequence[Node] = (),
        discover_topology: bool = True) -> FakeCluster:
    """Build a FakeCluster of TPU slices.

    slices: [(slice_name, kind)] with kind from topology.WELL_KNOWN
    (e.g. ("slice-a", "v5e-256")).  dcn_pods maps slice name -> DCN pod
    name (defaults to one shared pod "dcn-0").  When discover_topology,
    the hypernode controller runs once so the topology tree exists.
    """
    cluster = FakeCluster()
    for name, kind in slices:
        topo = slice_for(name, kind)
        pod = (dcn_pods or {}).get(name, "dcn-0")
        for node in slice_nodes(topo, dcn_pod=pod):
            cluster.add_node(node)
    for node in extra_nodes:
        cluster.add_node(node)

    if discover_topology:
        from volcano_tpu.controllers.hypernode import HyperNodeController
        ctrl = HyperNodeController()
        ctrl.initialize(cluster)
        ctrl.sync()
    return cluster


# -- chaos helpers (failover tooling / tests) --------------------------

def fail_host(cluster, node_name: str, provider=None,
              chips_healthy: int = 0):
    """Inject a host failure without hand-editing node objects.

    With *provider* (a FakeUsageProvider whose NodeAgent is being
    driven): flip the chip telemetry so the agent's K-consecutive-
    ticks hysteresis detects the failure the production way (drive
    agent.sync() yourself).  Without one: emulate the agent's FAILED
    endpoint directly — cordon, label, and post the SliceHealthReport
    the failover controller reacts to — for tests/chaos tools with no
    agent in the loop."""
    from volcano_tpu.api.resource import Resource
    from volcano_tpu.api.slicehealth import (SliceHealthReport,
                                             VERDICT_FAILED)
    from volcano_tpu.api.types import TPU_SLICE_LABEL
    node = cluster.nodes[node_name]
    detected = int(Resource.from_resource_list(node.allocatable)
                   .get(TPU)) or 4
    if provider is not None:
        provider.set(node_name, cpu_fraction=0.2,
                     tpu_chips_detected=detected,
                     tpu_chips_healthy=chips_healthy)
        return node
    from volcano_tpu.agent.agent import (AGENT_CORDONED_ANNOTATION,
                                         TPU_HEALTHY_LABEL)
    import time as _time
    node.labels[TPU_HEALTHY_LABEL] = "false"
    node.unschedulable = True
    node.annotations[AGENT_CORDONED_ANNOTATION] = "true"
    cluster.put_object("node", node)
    cluster.put_object("slicehealthreport", SliceHealthReport(
        node=node_name, slice=node.labels.get(TPU_SLICE_LABEL, ""),
        verdict=VERDICT_FAILED, chips_detected=detected,
        chips_healthy=chips_healthy, consecutive_bad=3,
        first_bad_ts=round(_time.time(), 3)))
    return node


def heal_host(cluster, node_name: str, provider=None):
    """Undo fail_host: healthy telemetry (provider mode) or a Healthy
    report + uncordon (direct mode)."""
    from volcano_tpu.api.resource import Resource
    from volcano_tpu.api.slicehealth import (SliceHealthReport,
                                             VERDICT_HEALTHY)
    from volcano_tpu.api.types import TPU_SLICE_LABEL
    node = cluster.nodes[node_name]
    detected = int(Resource.from_resource_list(node.allocatable)
                   .get(TPU)) or 4
    if provider is not None:
        provider.set(node_name, cpu_fraction=0.2,
                     tpu_chips_detected=detected,
                     tpu_chips_healthy=detected)
        return node
    from volcano_tpu.agent.agent import (AGENT_CORDONED_ANNOTATION,
                                         TPU_HEALTHY_LABEL)
    node.labels[TPU_HEALTHY_LABEL] = "true"
    if node.annotations.pop(AGENT_CORDONED_ANNOTATION, None):
        node.unschedulable = False
    cluster.put_object("node", node)
    cluster.put_object("slicehealthreport", SliceHealthReport(
        node=node_name, slice=node.labels.get(TPU_SLICE_LABEL, ""),
        verdict=VERDICT_HEALTHY, chips_detected=detected,
        chips_healthy=detected, consecutive_good=3))
    return node
