"""Simulated TPU cluster provisioning — the KWOK-analogue harness.

Reference parity: benchmark/scripts/create-kwok-nodes.sh +
create-hypernodes.sh (fake nodes + synthetic rack/spine topologies).
Here fake nodes are fake TPU slice hosts: correct GKE-style labels,
chips-per-host allocatable, worker ids and ICI coordinates, grouped
into DCN pods — so gang + topology scheduling is exercised at
hundreds-of-hosts scale with zero real machines (SURVEY.md §4.4).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from volcano_tpu.api.node_info import Node
from volcano_tpu.api.pod import Taint
from volcano_tpu.api.resource import TPU
from volcano_tpu.api.types import (
    TPU_COORDS_LABEL,
    TPU_SLICE_LABEL,
    TPU_TOPOLOGY_LABEL,
    TPU_WORKER_ID_LABEL,
)
from volcano_tpu.api.devices.tpu.topology import SliceTopology, slice_for
from volcano_tpu.cache.fake_cluster import FakeCluster
from volcano_tpu.controllers.hypernode import DCN_POD_LABEL

ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"


def slice_nodes(slice_topo: SliceTopology, dcn_pod: str = "",
                cpu_per_host: int = 112, mem_gi: int = 192) -> List[Node]:
    """Materialize one slice as its host nodes with full TPU labels."""
    nodes = []
    for worker in range(slice_topo.num_hosts):
        coords = slice_topo.host_coords(worker)
        labels = {
            TPU_SLICE_LABEL: slice_topo.name,
            TPU_TOPOLOGY_LABEL: "x".join(str(d) for d in slice_topo.topology),
            TPU_WORKER_ID_LABEL: str(worker),
            TPU_COORDS_LABEL: ",".join(str(c) for c in coords),
            ACCELERATOR_LABEL: slice_topo.accelerator,
        }
        if dcn_pod:
            labels[DCN_POD_LABEL] = dcn_pod
        nodes.append(Node(
            name=f"{slice_topo.name}-w{worker}",
            labels=labels,
            allocatable={"cpu": cpu_per_host, "memory": f"{mem_gi}Gi",
                         TPU: slice_topo.chips_per_host, "pods": 110},
        ))
    return nodes


def make_tpu_cluster(
        slices: Sequence[Tuple[str, str]],
        dcn_pods: Optional[Dict[str, str]] = None,
        extra_nodes: Sequence[Node] = (),
        discover_topology: bool = True) -> FakeCluster:
    """Build a FakeCluster of TPU slices.

    slices: [(slice_name, kind)] with kind from topology.WELL_KNOWN
    (e.g. ("slice-a", "v5e-256")).  dcn_pods maps slice name -> DCN pod
    name (defaults to one shared pod "dcn-0").  When discover_topology,
    the hypernode controller runs once so the topology tree exists.
    """
    cluster = FakeCluster()
    for name, kind in slices:
        topo = slice_for(name, kind)
        pod = (dcn_pods or {}).get(name, "dcn-0")
        for node in slice_nodes(topo, dcn_pod=pod):
            cluster.add_node(node)
    for node in extra_nodes:
        cluster.add_node(node)

    if discover_topology:
        from volcano_tpu.controllers.hypernode import HyperNodeController
        ctrl = HyperNodeController()
        ctrl.initialize(cluster)
        ctrl.sync()
    return cluster
