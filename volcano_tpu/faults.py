"""Deterministic, seedable fault injection: wire, disk, and clocks.

PR 4 proved the control plane survives the *clean* failure (kill -9);
a planet-scale scheduler mostly dies of *gray* failures — slow links,
dropped acks, duplicated retries, full disks, lying fsyncs, bit rot in
the WAL, wall clocks that jump.  This module is the one place those
faults are described, drawn, and counted:

  * A ``FaultPlan`` is a SEEDED set of ``FaultRule``s.  Every decision
    comes off one ``random.Random(seed)`` stream (under a lock, in
    rule order), so a chaos run that found a bug is replayed exactly
    by re-running the same plan — the seed is logged with every
    injection and ``tools/chaos_conductor.py --seed N`` rebuilds the
    identical schedule.
  * Injection SITES:
      - ``server``: the state server's HTTP handler consults the plan
        per request (state_server.py) — drop_request, drop_response
        (the ack-lost case: commit happens, the ack never arrives),
        delay, duplicate, reorder, http_503, reset, trickle.
      - ``proxy``: the reusable TCP proxy (tools/chaoslib.ChaosProxy)
        injects connection-level faults between any two components —
        blackhole, latency, reset, trickle.
      - ``disk``: durability.py routes WAL file ops through a
        ``FaultyVFS`` — ENOSPC on append, EIO on fsync, torn
        multi-record writes.
      - ``clock``: ``install_clock_faults`` skews/jumps the WALL clock
        (``time.time``) while the monotonic clock stays honest — the
        exact divergence leases and dedupe stamps must survive.
  * Every injected fault increments
    ``fault_injected_total{site,kind}`` (bounded label sets) and logs
    the plan seed, so a failing run names its own reproduction.

Plans serialize to/from a plain JSON doc and load from the
``VTP_FAULT_PLAN`` env var (inline JSON, or ``@/path/to/plan.json``)
so a subprocess server enables chaos without new wiring.  Post-hoc
corruption helpers (``flip_bit``, ``truncate_at``) cover what no live
shim can: bit rot discovered only at the next boot.
"""

from __future__ import annotations

import errno
import json
import logging
import os
import random
import threading
import time
from typing import List, Optional

log = logging.getLogger(__name__)

FAULT_PLAN_ENV = "VTP_FAULT_PLAN"

SITES = ("server", "proxy", "disk", "clock")
# bounded kind enum — these label fault_injected_total, so the set is
# closed (a cardinality test pins it, like the sched_*/elastic_* rule)
WIRE_KINDS = ("drop_request", "drop_response", "delay", "duplicate",
              "reorder", "http_503", "reset", "trickle",
              # shipped-segment corruption: a byte flipped inside one
              # framed WAL record on the /wal shipping lane (the JSON
              # envelope stays valid; only the follower's per-record
              # CRC can tell) — applied by the /wal route itself
              "corrupt_ship")
PROXY_KINDS = ("blackhole", "latency", "reset", "trickle")
DISK_KINDS = ("enospc_append", "eio_fsync", "torn_write")
CLOCK_KINDS = ("wall_jump", "wall_skew")
ALL_KINDS = tuple(dict.fromkeys(
    WIRE_KINDS + PROXY_KINDS + DISK_KINDS + CLOCK_KINDS))


class FaultRule:
    """One injectable fault: where, what, how often, and when.

    route   glob-ish match on the HTTP path ("*" = any; a trailing
            "*" matches a prefix) — meaningful at the server site
    prob    per-opportunity injection probability (drawn from the
            plan's seeded stream)
    after_s/until_s
            active window in seconds since plan start (until_s 0 =
            forever) — how the conductor schedules an ENOSPC brownout
            or a wall jump at a known offset
    ms      magnitude for delay/latency/trickle (per-chunk gap)
    offset_s
            wall-clock displacement for clock kinds
    max_injections
            hard cap (0 = unlimited) — "drop exactly the first ack"
    """

    __slots__ = ("site", "kind", "route", "prob", "after_s", "until_s",
                 "ms", "offset_s", "max_injections", "injected")

    def __init__(self, site: str, kind: str, route: str = "*",
                 prob: float = 1.0, after_s: float = 0.0,
                 until_s: float = 0.0, ms: float = 0.0,
                 offset_s: float = 0.0, max_injections: int = 0):
        if site not in SITES:
            raise ValueError(f"unknown fault site {site!r}")
        site_kinds = {"server": WIRE_KINDS, "proxy": PROXY_KINDS,
                      "disk": DISK_KINDS, "clock": CLOCK_KINDS}[site]
        if kind not in site_kinds:
            raise ValueError(
                f"fault kind {kind!r} is not injectable at site "
                f"{site!r} (valid: {', '.join(site_kinds)})")
        self.site = site
        self.kind = kind
        self.route = route
        self.prob = float(prob)
        self.after_s = float(after_s)
        self.until_s = float(until_s)
        self.ms = float(ms)
        self.offset_s = float(offset_s)
        self.max_injections = int(max_injections)
        self.injected = 0

    def matches_route(self, route: str) -> bool:
        if self.route in ("*", ""):
            return True
        if self.route.endswith("*"):
            return route.startswith(self.route[:-1])
        return route == self.route

    def to_doc(self) -> dict:
        doc = {"site": self.site, "kind": self.kind}
        for f in ("route", "prob", "after_s", "until_s", "ms",
                  "offset_s", "max_injections"):
            v = getattr(self, f)
            if v not in ("*", 0, 0.0) and not (f == "prob" and v == 1.0):
                doc[f] = v
        return doc

    @classmethod
    def from_doc(cls, doc: dict) -> "FaultRule":
        return cls(**{k: v for k, v in doc.items()
                      if k in cls.__slots__ and k != "injected"})


class FaultPlan:
    """A seeded fault schedule shared by every injection site in one
    process.  Decisions are deterministic GIVEN the sequence of
    opportunities: one locked RNG draw per (matching rule, chance),
    in rule order — so a single-threaded replay of the same request
    sequence injects the same faults, and a threaded run is replayable
    to the extent its request interleaving is."""

    def __init__(self, seed: int, rules: List[FaultRule],
                 t0: Optional[float] = None):
        self.seed = int(seed)
        self.rules = list(rules)
        self.rng = random.Random(self.seed)
        self.t0 = time.monotonic() if t0 is None else t0
        self._lock = threading.Lock()
        # reorder pen: the first parked request waits for a second one
        # (or its hold budget) so two in-flight requests swap order
        self._reorder_cv = threading.Condition(self._lock)
        self._reorder_waiting = 0

    # -- construction ---------------------------------------------------

    def to_doc(self) -> dict:
        return {"seed": self.seed,
                "rules": [r.to_doc() for r in self.rules]}

    @classmethod
    def from_doc(cls, doc: dict) -> "FaultPlan":
        return cls(int(doc.get("seed", 0)),
                   [FaultRule.from_doc(r) for r in doc.get("rules", [])])

    @classmethod
    def from_env(cls, env: Optional[dict] = None) -> Optional["FaultPlan"]:
        raw = (env if env is not None else os.environ).get(
            FAULT_PLAN_ENV, "")
        if not raw:
            return None
        if raw.startswith("@"):
            with open(raw[1:], encoding="utf-8") as f:
                raw = f.read()
        plan = cls.from_doc(json.loads(raw))
        log.warning("fault plan ACTIVE (seed=%d, %d rules) — this "
                    "process injects faults on purpose", plan.seed,
                    len(plan.rules))
        return plan

    # -- decisions ------------------------------------------------------

    def elapsed(self) -> float:
        return time.monotonic() - self.t0

    def _active(self, rule: FaultRule, now_s: float) -> bool:
        if rule.max_injections and \
                rule.injected >= rule.max_injections:
            return False
        if now_s < rule.after_s:
            return False
        if rule.until_s and now_s >= rule.until_s:
            return False
        return True

    def decide(self, site: str, route: str = "*",
               kinds=None) -> Optional[FaultRule]:
        """One injection opportunity: returns the first matching rule
        that fires, counting + logging it, or None.  kinds narrows to
        the fault kinds this opportunity can express (an append can
        suffer ENOSPC, never a lying fsync) — rules outside it are
        not consulted, so they neither fire nor burn their injection
        budget on the wrong seam."""
        now_s = self.elapsed()
        with self._lock:
            for rule in self.rules:
                if rule.site != site or not rule.matches_route(route):
                    continue
                if kinds is not None and rule.kind not in kinds:
                    continue
                if not self._active(rule, now_s):
                    continue
                if rule.prob < 1.0 and self.rng.random() >= rule.prob:
                    continue
                rule.injected += 1
                self._count(site, rule.kind, route)
                return rule
        return None

    def _count(self, site: str, kind: str, route: str) -> None:
        from volcano_tpu import metrics
        metrics.inc("fault_injected_total", site=site, kind=kind)
        log.info("fault injected: site=%s kind=%s route=%s seed=%d "
                 "(replay: same plan, same seed)", site, kind, route,
                 self.seed)

    def reorder_park(self, hold_s: float = 0.15) -> None:
        """The reorder fault: park this request until another request
        enters the pen (they swap order) or the hold budget runs out
        (nothing to swap with — degrade to a delay)."""
        with self._reorder_cv:
            if self._reorder_waiting > 0:
                # someone is parked: release them and pass through —
                # the two requests have now swapped
                self._reorder_waiting = 0
                self._reorder_cv.notify_all()
                return
            self._reorder_waiting += 1
            self._reorder_cv.wait(hold_s)
            if self._reorder_waiting > 0:    # timed out un-swapped
                self._reorder_waiting = 0

    def status(self) -> List[dict]:
        with self._lock:
            return [dict(r.to_doc(), injected=r.injected)
                    for r in self.rules]


# -- disk faults: the VFS shim durability.py routes file ops through --

class VFS:
    """Passthrough file ops.  DurableStore calls ONLY these for WAL
    writes, so a FaultyVFS can sit in the seam without durability.py
    knowing faults exist."""

    def open_append(self, path: str):
        return open(path, "a", encoding="utf-8")

    def write(self, f, data: str) -> None:
        f.write(data)

    def fsync(self, f) -> None:
        f.flush()
        os.fsync(f.fileno())


class DiskFault(OSError):
    """An injected disk error (still an OSError: callers handle it
    exactly like the real thing)."""


class FaultyVFS(VFS):
    """Plan-driven disk faults on the WAL seam.

    enospc_append  append raises ENOSPC, nothing written
    torn_write     append persists only a PREFIX of the buffer then
                   raises EIO (a multi-record write torn mid-line)
    eio_fsync      fsync raises EIO after flushing — the lying-fsync
                   shape: page cache took the bytes, the disk did not
    """

    def __init__(self, plan: FaultPlan):
        self.plan = plan

    def write(self, f, data: str) -> None:
        rule = self.plan.decide("disk", "append",
                                kinds=("enospc_append", "torn_write"))
        if rule is not None and rule.kind == "enospc_append":
            raise DiskFault(errno.ENOSPC, "injected: no space left "
                                          "on device")
        if rule is not None and rule.kind == "torn_write":
            f.write(data[:max(1, len(data) // 2)])
            f.flush()
            raise DiskFault(errno.EIO, "injected: torn write")
        f.write(data)

    def fsync(self, f) -> None:
        f.flush()
        rule = self.plan.decide("disk", "fsync",
                                kinds=("eio_fsync",))
        if rule is not None and rule.kind == "eio_fsync":
            raise DiskFault(errno.EIO, "injected: fsync I/O error")
        os.fsync(f.fileno())


# -- clock faults ----------------------------------------------------

_REAL_TIME = None


def install_clock_faults(plan: Optional[FaultPlan]) -> bool:
    """Skew/jump the WALL clock per the plan's clock rules while the
    monotonic clock stays honest — time.time is wrapped process-wide
    (chaos processes only; the plan env var is the opt-in).

    wall_jump  after after_s, time.time() returns real + offset_s
               (until until_s, then the jump reverts — an NTP step
               and its correction)
    wall_skew  offset grows linearly at offset_s per second inside
               the window (a drifting clock)
    Injection is counted once per rule, when its window first
    activates."""
    global _REAL_TIME
    rules = [r for r in (plan.rules if plan else [])
             if r.site == "clock"]
    if not rules:
        return False
    if _REAL_TIME is None:
        _REAL_TIME = time.time
    real_time = _REAL_TIME
    counted: set = set()

    def faulty_time():
        t = real_time()
        el = plan.elapsed()
        for i, r in enumerate(rules):
            if el < r.after_s or (r.until_s and el >= r.until_s):
                continue
            if i not in counted:
                counted.add(i)
                plan._count("clock", r.kind, "*")
            if r.kind == "wall_jump":
                t += r.offset_s
            elif r.kind == "wall_skew":
                t += r.offset_s * (el - r.after_s)
        return t

    time.time = faulty_time
    log.warning("clock faults installed: %d rule(s), seed=%d",
                len(rules), plan.seed)
    return True


def uninstall_clock_faults() -> None:
    global _REAL_TIME
    if _REAL_TIME is not None:
        time.time = _REAL_TIME
        _REAL_TIME = None


# -- post-hoc corruption (bit rot, operator accidents) ----------------

def flip_bit(path: str, offset: int, bit: int = 3) -> int:
    """Flip one bit of the byte at *offset* (negative = from EOF);
    returns the absolute offset touched.  The canonical bit-rot
    injection: the record still LOOKS like a line — only the CRC can
    tell."""
    size = os.path.getsize(path)
    if offset < 0:
        offset += size
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ (1 << bit)]))
    return offset


def flip_record_bit(path: str, record_index: int) -> int:
    """Flip a bit INSIDE the payload of the record_index'th line
    (0-based) of a WAL segment — mid-segment bit rot that still parses
    as a line.  Returns the absolute byte offset flipped."""
    with open(path, "rb") as f:
        lines = f.readlines()
    off = sum(len(ln) for ln in lines[:record_index])
    target = lines[record_index]
    # flip inside the JSON body, past the CRC frame, away from the
    # newline: a mid-payload flip that keeps the line a line
    return flip_bit(path, off + min(len(target) - 2,
                                    max(12, len(target) // 2)))


def truncate_at(path: str, nbytes: int) -> None:
    """Cut a file to *nbytes* (negative = drop that many from EOF) —
    the torn-final-record shape."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size + nbytes if nbytes < 0 else nbytes)
