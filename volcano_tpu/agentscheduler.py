"""Agent scheduler — the pod-at-a-time fast path.

Reference parity: pkg/agentscheduler (design docs/design/
agent-scheduler.md): latency-oriented scheduler for AI-agent workloads
(bursts of small, independent pods) running BESIDE the batch scheduler.
Own scheduling queue (active / backoff / unschedulable pools, vendored
kube-scheduler queue in the reference), multi-worker scheduling over a
shared incremental cache, and a conflict-aware binder using per-node
BindGeneration optimistic concurrency (api/node_info.go:100,
pkg/agentscheduler/cache/binder.go): a worker snapshots the
generation, picks K candidate nodes, and the bind commits only if the
generation is unchanged — otherwise the pod requeues urgent and tries
its next candidate.

Shard awareness: in hard mode only its NodeShard's nodes are
candidates; soft mode prefers them (allocate.go:886-919 analogue).
"""

from __future__ import annotations

import heapq
import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from volcano_tpu.api.job_info import TaskInfo
from volcano_tpu.api.node_info import NodeInfo
from volcano_tpu.framework.job_updater import (
    REASON_UNSCHEDULABLE,
    SCHEDULING_REASON_ANNOTATION,
)
from volcano_tpu.api.shard import (
    AGENT_SCHEDULER,
    SHARD_MODE_HARD,
    SHARD_MODE_NONE,
    SHARD_MODE_SOFT,
)
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.controllers.sharding import shard_nodes_for
from volcano_tpu import metrics

log = logging.getLogger(__name__)

DEFAULT_CANDIDATES = 3
MAX_BACKOFF = 8.0


# -- plugin framework (reference pkg/agentscheduler/{plugins,actions}) -
#
# The fast path mirrors the batch scheduler's plugin architecture at
# the size it needs: filter/score objects in an ordered chain, chosen
# per AgentScheduler instance.  The default chain reuses the BATCH
# predicate logic (selector/affinity/taints/ports/pod-count) and the
# TPU device shape rules, so a pod the batch path would reject can
# never be fast-path bound onto a TPU host (VERDICT r1 weak 3).

AGENT_PLUGINS: Dict[str, type] = {}


def register_agent_plugin(name: str):
    def deco(cls):
        AGENT_PLUGINS[name] = cls
        cls.name = name
        return cls
    return deco


class AgentPlugin:
    """Filter/score extension point for the fast path.

    filter_static: spec-vs-node checks that cannot change as pods bind
    (selector/affinity/taints) — the scheduler memoizes them per
    (pod-spec, node) between cache refreshes, the fast-path analogue of
    the batch path's per-spec fit-error cache (actions/allocate.py:185,
    reference predicates/cache.go).  filter: occupancy-dependent checks,
    re-run on every placement attempt.  A plugin that can't split
    leaves everything in filter — slower but always correct.

    MEMOIZATION CONTRACT: filter_static verdicts (and score ordering)
    are shared between pods whose _spec_signature is equal — by
    default that covers selector/affinity/tolerations/requests/ports
    ONLY.  A plugin whose filter_static or score reads any other pod
    field (labels, annotations, priority, ...) MUST return those
    fields from signature_extra() so pods differing there get their
    own cache entry; otherwise verdicts silently leak across pods."""

    name = "agent-plugin"

    def signature_extra(self, pod):
        """Hashable tuple of every pod field this plugin's
        filter_static/score reads BEYOND the default signature
        (see class docstring).  None = nothing extra."""
        return None

    def filter_static(self, task: TaskInfo, node: NodeInfo):
        """None = node passes; a Status-like truthy value rejects."""
        return None

    def filter(self, task: TaskInfo, node: NodeInfo):
        """None = node passes; a Status-like truthy value rejects."""
        return None

    def score(self, task: TaskInfo, node: NodeInfo) -> float:
        return 0.0


@register_agent_plugin("predicates")
class AgentPredicatesPlugin(AgentPlugin):
    """Node-local batch predicates — the SAME verdict functions the
    batch path runs, split along the memoization boundary: selector/
    affinity/taints are static, pod-count/ports re-check every bind."""

    def filter_static(self, task, node):
        from volcano_tpu.plugins.predicates import PredicatesPlugin
        return PredicatesPlugin._predicate_static(task, node)

    def filter(self, task, node):
        from volcano_tpu.plugins.predicates import PredicatesPlugin
        return PredicatesPlugin._predicate_dynamic(task, node)


@register_agent_plugin("resources")
class AgentResourcesPlugin(AgentPlugin):
    """Immediate idle fit (the fast path binds now — no pipelining)."""

    def filter(self, task, node):
        if not task.init_resreq.less_equal(node.idle):
            return "insufficient idle resources"
        return None


@register_agent_plugin("deviceshare")
class AgentDevicePlugin(AgentPlugin):
    """TPU shape rules (whole-host atomicity on multi-host slices,
    valid sub-host chip counts) via the registered device layer."""

    def filter(self, task, node):
        device = node.others.get("tpu")
        if device is not None and device.has_device_request(task):
            return device.filter_node(task)
        return None

    def score(self, task, node) -> float:
        device = node.others.get("tpu")
        if device is not None and device.has_device_request(task):
            return device.score_node(task)
        return 0.0


@register_agent_plugin("leastalloc")
class AgentLeastAllocPlugin(AgentPlugin):
    def score(self, task, node) -> float:
        s = 0.0
        for dim, cap in node.allocatable.res.items():
            if cap > 0.1:
                s += 1.0 - node.used.get(dim) / cap
        return s


DEFAULT_AGENT_PLUGINS = ["predicates", "resources", "deviceshare",
                         "leastalloc"]

SPEC_CACHE_MAX = 512     # heterogeneous-workload safety valve


def _spec_signature(pod) -> tuple:
    """Everything the filter/score chain reads off the POD (vs the
    node): two pods with equal signatures get identical verdicts, so
    static filtering + score ordering is shared across a burst
    (reference: per-spec fit-error memoization, job_info.go
    TaskHasFitErrors; batch analogue actions/allocate.py:185)."""
    return (
        tuple(sorted(pod.node_selector.items())),
        repr(pod.affinity_node_terms),
        tuple((t.key, t.operator, t.value, t.effect)
              for t in pod.tolerations),
        tuple(sorted(pod.resource_requests().res.items())),
        tuple(sorted(port for c in pod.containers for port in c.ports)),
    )


class _SpecEntry:
    """Per-spec candidate state: the statically-feasible nodes ordered
    by a lazily-revalidated max-heap.  scores holds the authoritative
    last-computed score per node; heap entries whose score disagrees
    are stale duplicates and are dropped on pop."""

    __slots__ = ("heap", "scores")

    def __init__(self):
        self.heap: List[Tuple[float, str]] = []     # (-score, node name)
        self.scores: Dict[str, float] = {}


class SchedulingQueue:
    """active / backoff / unschedulable pools (third_party kube queue).

    Thread-safe: workers pop and watch callbacks push from arbitrary
    threads.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.active: deque = deque()
        self.backoff: List[Tuple[float, object]] = []   # (ready_at, pod)
        self.unschedulable: Dict[str, object] = {}
        self._parked_gates: Dict[str, list] = {}   # gates at park time
        self._seen: set = set()

    def push(self, pod, urgent: bool = False):
        with self._lock:
            self._push_locked(pod, urgent)

    def _push_locked(self, pod, urgent: bool = False):
        if pod.key in self.unschedulable:
            # reactivate only on a schedulability-relevant change (a
            # lifted gate, compared against the gates recorded at park
            # time); immaterial status writes must not turn N parked
            # pods into a continuous full-rescan loop —
            # capacity-driven retries stay on activate_unschedulable
            if self._parked_gates.get(pod.key) == \
                    list(getattr(pod, "scheduling_gates", [])):
                # keep the freshest object so a later
                # activate_unschedulable retries the updated spec
                self.unschedulable[pod.key] = pod
                return
            del self.unschedulable[pod.key]
            self._parked_gates.pop(pod.key, None)
        if pod.key in self._seen:
            return
        self._seen.add(pod.key)
        if urgent:
            self.active.appendleft(pod)
        else:
            self.active.append(pod)

    def requeue_backoff(self, pod, attempt: int):
        delay = min(MAX_BACKOFF, 0.05 * (2 ** attempt))
        with self._lock:
            self.backoff.append((time.time() + delay, pod))
            self._seen.discard(pod.key)

    def park_unschedulable(self, pod):
        with self._lock:
            self.unschedulable[pod.key] = pod
            self._parked_gates[pod.key] = \
                list(getattr(pod, "scheduling_gates", []))
            self._seen.discard(pod.key)

    def _flush_ready_locked(self):
        now = time.time()
        still = []
        for ready_at, pod in self.backoff:
            if ready_at <= now:
                self._push_locked(pod)
            else:
                still.append((ready_at, pod))
        self.backoff = still

    def activate_unschedulable(self):
        """Cluster changed: give parked pods another chance."""
        with self._lock:
            parked, self.unschedulable = self.unschedulable, {}
            self._parked_gates.clear()
            for pod in parked.values():
                self._push_locked(pod)

    def pop(self):
        with self._lock:
            self._flush_ready_locked()
            if not self.active:
                return None
            pod = self.active.popleft()
            self._seen.discard(pod.key)
            return pod

    def __len__(self):
        with self._lock:
            return len(self.active) + len(self.backoff) + \
                len(self.unschedulable)


class AgentScheduler:
    """Per-pod scheduler over an incrementally-maintained node cache."""

    def __init__(self, cluster, scheduler_name: str = AGENT_SCHEDULER,
                 shard_mode: str = SHARD_MODE_NONE,
                 candidates: int = DEFAULT_CANDIDATES,
                 plugins: Optional[List[str]] = None):
        self.cluster = cluster
        self.scheduler_name = scheduler_name
        self.shard_mode = shard_mode
        self.candidates = candidates
        names = plugins if plugins is not None else DEFAULT_AGENT_PLUGINS
        self.plugins: List[AgentPlugin] = []
        for name in names:
            cls = AGENT_PLUGINS.get(name)
            if cls is None:
                log.warning("unknown agent plugin %s (skipped)", name)
                continue
            self.plugins.append(cls())
        self.queue = SchedulingQueue()
        # plugins that OVERRIDE signature_extra (precomputed: calling
        # the default no-op per pod per plugin cost ~25% of the fast
        # path's throughput)
        self._sig_plugins = [
            p for p in self.plugins
            if type(p).signature_extra is not AgentPlugin.signature_extra]
        self.nodes: Dict[str, NodeInfo] = {}
        self._attempts: Dict[str, int] = {}
        self._spec_cache: Dict[tuple, _SpecEntry] = {}
        self._shard: frozenset = frozenset()
        self._lock = threading.Lock()
        cluster.watch(self._on_event)
        self.refresh()

    # -- cache maintenance (incremental, not per-cycle snapshot) -------

    def refresh(self):
        from volcano_tpu.cache.cache import REGISTERED_DEVICES
        snap = self.cluster.list_all()
        shard = frozenset(shard_nodes_for(self.cluster,
                                          self.scheduler_name))
        with self._lock:
            self._shard = shard
            self._spec_cache.clear()     # node set/labels may have changed
            self.nodes = {n.name: NodeInfo(n) for n in snap.nodes}
            # device enrichment: the fast path enforces the same TPU
            # shape rules as the batch path
            for ni in self.nodes.values():
                for name, factory in REGISTERED_DEVICES.items():
                    ni.others[name] = factory(ni)
            for pod in snap.pods:
                if pod.node_name and pod.node_name in self.nodes and \
                        pod.phase in (TaskStatus.RUNNING, TaskStatus.BOUND,
                                      TaskStatus.BINDING):
                    try:
                        self.nodes[pod.node_name].add_task(TaskInfo(pod))
                    except (KeyError, ValueError):
                        pass
            for pod in snap.pods:
                if pod.scheduler_name == self.scheduler_name and \
                        pod.phase is TaskStatus.PENDING and not pod.node_name:
                    self.queue.push(pod)

    def _on_event(self, kind: str, obj):
        if kind == "pod" and getattr(obj, "scheduler_name", "") == \
                self.scheduler_name and obj.phase is TaskStatus.PENDING \
                and not obj.node_name:
            self.queue.push(obj)
        elif kind in ("pod_deleted", "node", "node_deleted",
                      "nodeshard", "nodeshard_deleted"):
            # keep the incremental cache honest: rebuild node state
            # before reconsidering parked pods (a new node must be a
            # candidate; a dead node must stop being one)
            self.refresh()
            self.queue.activate_unschedulable()

    # -- scheduling ----------------------------------------------------

    def _score(self, task: TaskInfo, node: NodeInfo) -> float:
        s = sum(p.score(task, node) for p in self.plugins)
        if self._shard and self.shard_mode == SHARD_MODE_SOFT and \
                node.name in self._shard:
            s += 100.0   # strong shard preference
        return s

    def _spec_entry(self, task: TaskInfo) -> _SpecEntry:
        sig = _spec_signature(task.pod)
        if self._sig_plugins:
            sig += tuple(
                (p.name, e) for p in self._sig_plugins
                if (e := p.signature_extra(task.pod)) is not None)
        entry = self._spec_cache.get(sig)
        if entry is not None:
            return entry
        if len(self._spec_cache) >= SPEC_CACHE_MAX:
            self._spec_cache.clear()
        entry = _SpecEntry()
        for node in self.nodes.values():
            if self._shard and self.shard_mode == SHARD_MODE_HARD and \
                    node.name not in self._shard:
                continue
            if any(p.filter_static(task, node) is not None
                   for p in self.plugins):
                continue
            s = self._score(task, node)
            entry.scores[node.name] = s
            entry.heap.append((-s, node.name))
        heapq.heapify(entry.heap)
        self._spec_cache[sig] = entry
        return entry

    def _candidate_nodes(self, task: TaskInfo) -> List[NodeInfo]:
        """Top-K dynamically-feasible nodes for the task, best score
        first.  Static filtering + ordering come from the per-spec
        heap; entries are revalidated lazily on pop (a bind only moves
        the bound node's score, so a burst of same-spec pods pays
        O(K log N) each instead of O(N * plugins))."""
        entry = self._spec_entry(task)
        heap = entry.heap
        result: List[NodeInfo] = []
        repush: List[Tuple[float, str]] = []
        while heap and len(result) < self.candidates:
            neg, name = heapq.heappop(heap)
            if entry.scores.get(name) != -neg:
                continue                       # stale duplicate
            node = self.nodes.get(name)
            if node is None:                   # node gone since refresh
                del entry.scores[name]
                continue
            s = self._score(task, node)
            if s != -neg:                      # occupancy moved: freshen
                entry.scores[name] = s
                heapq.heappush(heap, (-s, name))
                continue
            repush.append((neg, name))
            if any(p.filter(task, node) is not None
                   for p in self.plugins):
                continue                       # infeasible right now
            result.append(node)
        for item in repush:
            heapq.heappush(heap, item)
        return result

    def _select_candidates(self, task) -> List[Tuple[NodeInfo, int]]:
        """Top-K feasible nodes with their generation at selection time
        (the optimistic-concurrency read point)."""
        with self._lock:
            return [(n, n.bind_generation)
                    for n in self._candidate_nodes(task)]

    def _unschedulable_reason(self, task) -> str:
        """Compact why-not for a pod with zero candidates, from the
        spec-cache view (O(1) — the entry was just computed).  Locked:
        _spec_entry mutates the shared cache and iterates self.nodes,
        both of which concurrent workers / watch refreshes touch.  In
        hard shard mode the denominator is the SHARD (the evaluated
        universe), not the whole cluster."""
        with self._lock:
            entry = self._spec_entry(task)
            static_ok = len(entry.scores)
            if self._shard and self.shard_mode == SHARD_MODE_HARD:
                total = len(self._shard & set(self.nodes))
                scope = "in-shard node(s)"
            else:
                total = len(self.nodes)
                scope = "node(s)"
        if static_ok == 0:
            return (f"0/{total} {scope} pass static filters "
                    f"(selector/affinity/taints/device shape)")
        return (f"{static_ok}/{total} {scope} pass static filters but "
                f"none can host the pod now (occupancy: resources/"
                f"ports/pod count)")

    def schedule_one(self) -> Optional[str]:
        """Pop one pod, place it; returns bound node name or None."""
        placed = self._place_one()
        if placed is None:
            return None
        pod, task, node, attempt, t0, ts_alloc = placed
        try:
            self.cluster.bind_pod(pod.namespace, pod.name, node.name,
                                  ts_alloc=ts_alloc)
            err = None
        except Exception as e:  # noqa: BLE001 - conflict path
            err = str(e) or type(e).__name__
        return self._commit_bind(pod, task, node, attempt, t0,
                                 ts_alloc, err)

    def _place_one(self):
        """Pop one pod and RESERVE a node for it in the local cache —
        the optimistic half of the bind (add_task + generation bump) —
        without committing to the cluster.  Returns
        (pod, task, node, attempt, t0) or None (empty queue, gated,
        parked unschedulable, or sent to backoff).  schedule_one
        commits immediately; run_until_drained's batched lane commits
        many reservations as one bind_pods call."""
        pod = self.queue.pop()
        if pod is None:
            return None
        if pod.phase is not TaskStatus.PENDING or pod.node_name:
            return None  # stale queue entry: already bound elsewhere
        if pod.scheduling_gates:
            # gated pods wait for the gate manager, exactly like the
            # batch path's pre-predicate
            self.queue.park_unschedulable(pod)
            return None
        task = TaskInfo(pod)
        # account the placement immediately: BINDING occupies resources
        # (a PENDING task consumes nothing and would allow overbinding)
        task.status = TaskStatus.BINDING
        attempt = self._attempts.get(pod.key, 0)

        t0 = time.perf_counter()
        candidates = self._select_candidates(task)
        if not candidates:
            # park FIRST, then publish: put_object's synchronous watch
            # echo (RemoteCluster) pushes the echoed pod back into the
            # queue, and the parked-key branch of _push_locked swaps in
            # that freshest copy — publishing first would land the echo
            # in the ACTIVE pool alongside the stale copy we then park
            self.queue.park_unschedulable(pod)
            reason = self._unschedulable_reason(task)
            if pod.annotations.get(SCHEDULING_REASON_ANNOTATION) != \
                    REASON_UNSCHEDULABLE or pod.status_message != reason:
                pod.annotations[SCHEDULING_REASON_ANNOTATION] = \
                    REASON_UNSCHEDULABLE
                pod.status_message = reason
                try:
                    self.cluster.put_object("pod", pod)
                except Exception:  # noqa: BLE001 — status is advisory
                    log.debug("reason publish failed for %s", pod.key)
            metrics.inc("agent_unschedulable_total")
            return None

        for node, generation in candidates:
            with self._lock:
                if node.bind_generation != generation:
                    continue  # lost the race to another worker
                try:
                    node.add_task(task)
                except (KeyError, ValueError):
                    continue
                node.bind_generation += 1
            # wall-clock decision stamp for the `allocated` lifecycle
            # phase (t0 is a perf counter, useless across processes)
            return pod, task, node, attempt, t0, time.time()

        self._attempts[pod.key] = attempt + 1
        self.queue.requeue_backoff(pod, attempt)
        return None

    def _commit_bind(self, pod, task, node, attempt, t0, _ts_alloc,
                     err) -> Optional[str]:
        """Finish one reservation given the cluster's bind verdict —
        IDENTICAL bookkeeping for the per-pod and batched lanes.
        Success clears attempts and any stale unschedulable reason;
        failure rolls the reservation back and requeues urgent."""
        if err is not None:
            with self._lock:
                node.remove_task(task)
            log.debug("agent bind conflict for %s on %s: %s",
                      pod.key, node.name, err)
            self._attempts[pod.key] = attempt + 1
            self.queue.push(pod, urgent=True)
            metrics.inc("agent_bind_conflicts_total")
            return None
        metrics.observe("agent_pod_e2e_latency_seconds",
                        time.perf_counter() - t0)
        self._attempts.pop(pod.key, None)
        if SCHEDULING_REASON_ANNOTATION in pod.annotations:
            # a previously-parked pod placed: drop the stale
            # reason AND persist — bind_pod's POST carries only
            # node/phase, so without this write the apiserver copy
            # stays marked Unschedulable while running
            del pod.annotations[SCHEDULING_REASON_ANNOTATION]
            pod.status_message = ""
            try:
                self.cluster.put_object("pod", pod)
            except Exception:  # noqa: BLE001 — status is advisory
                log.debug("reason clear failed for %s", pod.key)
        return node.name

    def run_until_drained(self, max_iters: int = 100000,
                          bind_batch: int = 0) -> int:
        """Drain the active queue (tests/benchmarks/the wire agent
        process); returns bound count.

        bind_batch > 1 switches to the wire fast lane: up to that many
        pods are RESERVED against the local cache (the same optimistic
        add_task discipline), then their binds commit as ONE
        cluster.bind_pods call — a 500-pod burst costs ~8 round-trips
        at batch 64 instead of 500.  Per-item verdicts feed the exact
        same rollback/requeue bookkeeping as the per-pod lane, so a
        conflict on one pod still only requeues that pod."""
        bound = 0
        if bind_batch <= 1:
            for _ in range(max_iters):
                if not self.queue.active:
                    break
                if self.schedule_one() is not None:
                    bound += 1
            return bound
        iters = 0
        while iters < max_iters:
            placements = []
            while len(placements) < bind_batch and iters < max_iters \
                    and self.queue.active:
                iters += 1
                placed = self._place_one()
                if placed is not None:
                    placements.append(placed)
            if not placements:
                break
            errors = self.cluster.bind_pods(
                [(p.namespace, p.name, node.name, ts)
                 for p, _, node, _, _, ts in placements])
            for placed, err in zip(placements, errors):
                if self._commit_bind(*placed, err) is not None:
                    bound += 1
        return bound
